"""Sharded optimistic-concurrency scheduling: partition integrity, shard
leases (claim/steal/shed), the cross-shard device-claim guard, conflict
re-queue, and revision order under concurrent shard binds."""

import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, LeaseSet
from kubernetes1_tpu.machinery import Conflict
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.scheduler.cache import ExtendedResourceInfo
from kubernetes1_tpu.scheduler.devices import find_double_allocations
from kubernetes1_tpu.scheduler.sharding import pod_shard, shard_of

from .helpers import make_node, make_tpu_pod


# ------------------------------------------------------------ partitioning


class TestShardOf:
    def test_deterministic_and_in_range(self):
        for shards in (1, 2, 4, 7):
            for i in range(50):
                s = shard_of("ns", f"pod-{i}", shards)
                assert 0 <= s < shards
                assert s == shard_of("ns", f"pod-{i}", shards)

    def test_shards_one_is_always_zero(self):
        assert shard_of("any", "thing", 1) == 0
        assert shard_of("any", "thing", 0) == 0

    def test_distribution_covers_every_shard(self):
        shards = 4
        seen = {shard_of("ns", f"p-{i}", shards) for i in range(1000)}
        assert seen == set(range(shards))

    def test_gang_members_never_split(self):
        """The partition key is the GANG id, not the member name: every
        member of a gang lands on one shard regardless of its own name."""
        for shards in (2, 4, 8):
            for g in range(20):
                members = [make_tpu_pod(f"m-{g}-{i}", gang=f"gang-{g}",
                                        gang_size=8) for i in range(8)]
                got = {pod_shard(p, shards) for p in members}
                assert len(got) == 1, f"gang-{g} split across {got}"

    def test_namespace_is_part_of_the_key(self):
        vals = {shard_of(f"ns-{i}", "same-name", 16) for i in range(64)}
        assert len(vals) > 1


class TestDeviceRefcount:
    def test_overlapping_holders_keep_chip_unavailable(self):
        """Two holders of one chip (this shard's in-flight assumed loser
        + the peer's confirmed winner) must keep the chip unavailable
        until BOTH release — the set semantics freed it at the first
        release and livelocked the conflict retry loop."""
        info = ExtendedResourceInfo()
        info.set_devices([t.ExtendedResourceDevice(id="c0"),
                          t.ExtendedResourceDevice(id="c1")])
        assert info.available_count() == 2
        info.use(["c0"])   # assumed by this instance's pod
        info.use(["c0"])   # peer's winner arrives off the watch
        assert info.available_count() == 1
        info.release(["c0"])  # loser's forget
        assert info.available_count() == 1, \
            "chip freed while the winner still holds it"
        info.release(["c0"])  # winner's pod eventually removed
        assert info.available_count() == 2


# ------------------------------------------------------------ shard leases


@pytest.mark.slow
class TestLeaseSet:
    def test_split_steal_and_single_owner(self):
        master = Master().start()
        try:
            SH = 4
            a = LeaseSet(Clientset(master.url), "ls-test", "inst-a", SH,
                         lease_duration=1.5, retry_period=0.2).start()
            assert a.wait_for_any(10)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and len(a.owned()) < SH:
                time.sleep(0.1)
            assert a.owned() == frozenset(range(SH))  # single owner: all

            b = LeaseSet(Clientset(master.url), "ls-test", "inst-b", SH,
                         lease_duration=1.5, retry_period=0.2).start()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                oa, ob = a.owned(), b.owned()
                if oa and ob and not (oa & ob) \
                        and (oa | ob) == set(range(SH)):
                    break
                time.sleep(0.1)
            assert a.owned() and b.owned(), "join never rebalanced"
            assert not (a.owned() & b.owned())
            assert (a.owned() | b.owned()) == set(range(SH))

            # CRASH a (no release): b must steal at lease expiry
            a._stop.set()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and len(b.owned()) < SH:
                time.sleep(0.1)
            assert b.owned() == frozenset(range(SH)), "steal failed"
            b.stop()
        finally:
            master.stop()


# ------------------------------------------------- device-claim conflicts


def _binding(pod_name, node, ids):
    b = t.Binding(target_node=node,
                  extended_resource_assignments={f"{pod_name}-tpu": ids})
    b.metadata.name = pod_name
    b.metadata.namespace = "default"
    return b


class TestDeviceClaimGuard:
    def test_loser_gets_marked_conflict_and_winner_sticks(self):
        master = Master().start()
        try:
            cs = Clientset(master.url)
            for n in ("w", "l"):
                cs.pods.create(make_tpu_pod(n, tpus=1))
            cs.bind("default", "w", _binding("w", "node-1", ["chip-0"]))
            with pytest.raises(Conflict) as ei:
                cs.bind("default", "l", _binding("l", "node-1", ["chip-0"]))
            assert t.DEVICE_CLAIM_CONFLICT in str(ei.value)
            assert master.registry.device_claim_conflicts == 1
            # loser re-binds on a free chip
            cs.bind("default", "l", _binding("l", "node-1", ["chip-1"]))
            cs.close()
        finally:
            master.stop()

    def test_claim_frees_after_holder_hard_delete(self):
        master = Master().start()
        try:
            cs = Clientset(master.url)
            cs.pods.create(make_tpu_pod("a", tpus=1))
            cs.bind("default", "a", _binding("a", "node-1", ["chip-0"]))
            cs.pods.delete("a", "default", grace_seconds=0)
            cs.pods.create(make_tpu_pod("b", tpus=1))
            # stale claim validated against the store and purged
            cs.bind("default", "b", _binding("b", "node-1", ["chip-0"]))
            cs.close()
        finally:
            master.stop()

    def test_batch_race_loses_exactly_one(self):
        master = Master().start()
        try:
            cs = Clientset(master.url)
            cs.pods.create(make_tpu_pod("d", tpus=1))
            cs.pods.create(make_tpu_pod("e", tpus=1))
            outs = cs.bind_batch("default", [
                _binding("d", "node-2", ["chip-9"]),
                _binding("e", "node-2", ["chip-9"])])
            assert outs[0] is None
            assert outs[1] is not None
            assert t.DEVICE_CLAIM_CONFLICT in str(outs[1])
            cs.close()
        finally:
            master.stop()

    def test_batch_store_failure_releases_claims(self):
        """A mid-batch store failure must not leave the batch's chips
        claimed for the pending grace window: unconfirmed claims release
        on the exception path and the chips are immediately claimable."""
        master = Master().start()
        try:
            cs = Clientset(master.url)
            cs.pods.create(make_tpu_pod("x", tpus=1))
            orig = master.registry.store.commit_batch

            def boom(ops):
                raise ConnectionError("store died mid-batch")

            master.registry.store.commit_batch = boom
            with pytest.raises(ConnectionError):
                master.registry.bind_batch(
                    "default", [_binding("x", "n1", ["c0"])])
            master.registry.store.commit_batch = orig
            assert not master.registry._device_claims
            cs.pods.create(make_tpu_pod("y", tpus=1))
            assert cs.bind_batch(
                "default", [_binding("y", "n1", ["c0"])]) == [None]
            cs.close()
        finally:
            master.stop()

    def test_scheduler_requeues_on_claim_conflict(self):
        """The DEVICE_CLAIM_CONFLICT marker flips Conflict from terminal
        (pod already bound) to retryable (chip race lost): the pod goes
        back to the queue with backoff."""
        master = Master().start()
        try:
            sched = Scheduler(Clientset(master.url))
            pod = make_tpu_pod("loser", tpus=1)
            from kubernetes1_tpu.scheduler.scheduler import _BindItem

            item = _BindItem(pod, pod.clone(), None, None, None, "")
            sched._bind_failed(item, Conflict(
                f"{t.DEVICE_CLAIM_CONFLICT}: google.com/tpu chip c on "
                f"node n is held by pod x"))
            assert int(sched._bind_conflicts_ctr.value) == 1
            assert sched.queue.depth() == 1  # backing off, not dropped
            # plain Conflict stays terminal: no requeue
            item2 = _BindItem(pod, pod.clone(), None, None, None, "")
            sched._bind_failed(item2, Conflict("pod already bound to n2"))
            assert sched.queue.depth() == 1
        finally:
            master.stop()


# ------------------------------------------------------- two-shard racing


class TestTwoShardRace:
    def test_conflict_retry_e2e_zero_double_allocations(self):
        """Both shards race the same small chip pool: losers re-queue and
        land elsewhere; nothing double-allocates; everything binds."""
        master = Master().start()
        scheds = []
        try:
            cs = Clientset(master.url)
            for i in range(4):
                cs.nodes.create(make_node(
                    f"rn{i}", cpu="64", memory="256Gi", tpus=8,
                    slice_id=f"rs{i}", host_index=0))
            for k in range(2):
                s = Scheduler(Clientset(master.url), shards=2,
                              owned_shards={k}, identity=f"race-{k}")
                s.start()
                scheds.append(s)
            N = 24
            for i in range(N):
                cs.pods.create(make_tpu_pod(f"rp-{i}", tpus=1))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                pods, _ = cs.pods.list(namespace="default")
                if sum(1 for p in pods if p.spec.node_name) >= N:
                    break
                time.sleep(0.2)
            pods, _ = cs.pods.list(namespace="default")
            bound = [p for p in pods if p.spec.node_name]
            assert len(bound) == N, \
                f"only {len(bound)}/{N} bound; conflicts=" \
                f"{master.registry.device_claim_conflicts}"
            assert not find_double_allocations(pods)
            # BOTH instances actually scheduled their partition
            assert all(s.schedule_attempts > 0 for s in scheds)
            cs.close()
        finally:
            for s in scheds:
                s.stop()
            master.stop()

    def test_revision_order_strict_under_concurrent_shard_binds(self):
        """Two clients bind disjoint pod sets concurrently through the
        bulk path: every watch consumer must still observe the pod
        collection's commits in strictly increasing revision order."""
        master = Master().start()
        try:
            cs = Clientset(master.url)
            N = 16
            for i in range(N):
                cs.pods.create(make_tpu_pod(f"op-{i}", tpus=1))
            start_rev = master.store.current_revision()
            w = master.store.watch("/registry/pods/", start_rev)

            def bind_half(k):
                ccs = Clientset(master.url)
                outs = ccs.bind_batch("default", [
                    _binding(f"op-{i}", f"on-{k}", [f"oc-{k}-{i}"])
                    for i in range(k, N, 2)])
                assert all(o is None for o in outs), outs
                ccs.close()

            threads = [threading.Thread(target=bind_half, args=(k,))
                       for k in range(2)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            revs = []
            deadline = time.monotonic() + 10
            while len(revs) < N and time.monotonic() < deadline:
                evs = w.next_batch_timeout(1.0)
                for ev in evs or []:
                    revs.append(int(
                        ev.object["metadata"]["resourceVersion"]))
            w.stop()
            assert len(revs) == N
            assert revs == sorted(revs) and len(set(revs)) == N, \
                f"revision order violated: {revs}"
            cs.close()
        finally:
            master.stop()


class TestBulkFallbackThroughPool:
    def test_envelope_failure_drains_via_workers(self):
        """A dead bulk endpoint must not serialize the batch in one
        worker: items re-enter the bind queue marked single and the pool
        drains them as singleton binds."""
        master = Master().start()
        sched = None
        try:
            cs = Clientset(master.url)
            for i in range(2):
                cs.nodes.create(make_node(
                    f"fn{i}", cpu="64", memory="256Gi", tpus=8,
                    slice_id=f"fs{i}", host_index=0))
            scs = Clientset(master.url)

            def broken_bind_batch(namespace, bindings):
                raise RuntimeError("bulk endpoint disabled")

            scs.bind_batch = broken_bind_batch
            sched = Scheduler(scs)
            sched.start()
            N = 12
            for i in range(N):
                cs.pods.create(make_tpu_pod(f"fp-{i}", tpus=1))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                pods, _ = cs.pods.list(namespace="default")
                if sum(1 for p in pods if p.spec.node_name) >= N:
                    break
                time.sleep(0.2)
            pods, _ = cs.pods.list(namespace="default")
            assert sum(1 for p in pods if p.spec.node_name) == N
            assert not find_double_allocations(pods)
            # the fallback path actually engaged (or every drain was a
            # batch of one — force at least one real batch by checking
            # the counter only when batches formed)
            if sched.bind_batch_size.count and \
                    (sched.bind_batch_size.quantile(0.99) or 1) > 1:
                assert int(sched._bulk_fallbacks_ctr.value) > 0
            cs.close()
        finally:
            if sched is not None:
                sched.stop()
            master.stop()


# ------------------------------------------------------------ sharded e2e


@pytest.mark.slow
class TestLeasedShardE2E:
    def test_kill_one_instance_survivor_steals_and_drains(self):
        """tests-tier twin of scripts/chaos.py run_sched_shard_schedule
        (without wire faults): split ownership, crash one instance
        without releasing, survivor steals every shard and binds the
        orphaned backlog; zero double allocations."""
        master = Master().start()
        s_a = s_b = None
        try:
            cs = Clientset(master.url)
            for i in range(4):
                cs.nodes.create(make_node(
                    f"ln{i}", cpu="64", memory="256Gi", tpus=8,
                    slice_id=f"ls{i}", host_index=0))
            kw = dict(shards=4, shard_lease=True,
                      shard_lease_duration=1.5, shard_retry_period=0.2)
            s_a = Scheduler(Clientset(master.url), identity="lz-a", **kw)
            s_b = Scheduler(Clientset(master.url), identity="lz-b", **kw)
            s_a.start()
            s_b.start()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not (
                    s_a.owned_shards() and s_b.owned_shards()):
                time.sleep(0.1)
            assert s_a.owned_shards() and s_b.owned_shards()
            N = 24
            for i in range(N):
                cs.pods.create(make_tpu_pod(f"lp-{i}", tpus=1))
            # crash a: stop its lease loop WITHOUT releasing
            s_a._lease_set._stop.set()
            s_a._lease_set._owned = frozenset()
            s_a.stop()
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                pods, _ = cs.pods.list(namespace="default")
                if sum(1 for p in pods if p.spec.node_name) >= N \
                        and len(s_b.owned_shards()) == 4:
                    break
                time.sleep(0.2)
            pods, _ = cs.pods.list(namespace="default")
            assert sum(1 for p in pods if p.spec.node_name) == N
            assert s_b.owned_shards() == frozenset(range(4))
            assert not find_double_allocations(pods)
            cs.close()
        finally:
            for s in (s_b, s_a):
                if s is not None:
                    s.stop()
            master.stop()
