import os
import sys

# Force a deterministic 8-device virtual CPU mesh for all JAX-touching tests:
# multi-chip sharding is validated on virtual devices (the driver separately
# dry-runs the multichip path), single-real-chip runs happen only in bench.py.
#
# Note: this image registers the real-TPU "axon" platform from a
# sitecustomize hook that overrides the JAX_PLATFORMS env var, so the env
# var alone is not enough — we must also flip jax.config after import
# (config wins over the boot-time registration).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Runtime lock sanitizer (utils/locksan): every control-plane lock created
# after this point (and in every spawned server subprocess, via env
# inheritance) checks lock-order cycles and hold-time budgets.  setdefault
# so `KTPU_LOCKSAN=0 pytest ...` can switch it off for A/B timing runs.
os.environ.setdefault("KTPU_LOCKSAN", "1")

# Shared-object mutation sanitizer (utils/mutsan): informer caches and the
# apiserver watch cache hand out freezing proxies — an in-place mutation of
# a shared snapshot raises SharedObjectMutationError at the mutation site
# instead of silently corrupting cached state/serialized bytes.  setdefault
# so `KTPU_MUTSAN=0 pytest ...` can A/B a suspected sanitizer-induced
# failure, exactly like KTPU_LOCKSAN above.
os.environ.setdefault("KTPU_MUTSAN", "1")

# Dispatcher-blocking sanitizer (utils/loopsan): the shared event loop's
# thread is marked, and the classic blocking primitives (time.sleep,
# blocking socket I/O, queue.get, Future.result) raise
# BlockingOnDispatcherError with the callback's registration site when
# they run on it — the runtime twin of the KTPU016 static pass.  Same
# A/B switch shape as its siblings: `KTPU_LOOPSAN=0 pytest ...`.
os.environ.setdefault("KTPU_LOOPSAN", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------- leak police
#
# Round 4 left ten leaked store/apiserver pairs on the box (fixture setup
# failures skipped the post-yield teardown), and those stragglers poisoned
# every later benchmark.  The suite now polices itself: any framework
# process that appears during the run and survives it FAILS the session.

def _ktpu_procs(marker: str = "") -> dict:
    """pid -> cmdline of leak suspects.

    Without a marker (session-start warning): framework processes by
    cmdline (`-m kubernetes1_tpu` / the native `bin/ktpu-*`).

    With a marker (session-end check): ANY process whose ENVIRON carries
    it — i.e. every descendant of this pytest run, even after
    re-parenting.  Matching by marker alone matters: a leaked pod
    CONTAINER runs an arbitrary command (a `python -c http.server` from
    the port-forward test leaked exactly this way) and would slip a
    cmdline filter, while a concurrent session's processes can never
    carry our marker and so can never fail our run."""
    out = {}
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace").replace("\0", " ")
        except OSError:
            continue
        if marker:
            try:
                with open(f"/proc/{pid}/environ", "rb") as f:
                    if marker.encode() not in f.read():
                        continue
            except OSError:
                continue
        elif "-m kubernetes1_tpu" not in cmd and "bin/ktpu-" not in cmd:
            continue
        out[int(pid)] = cmd.strip()
    return out


import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "thread_leak_ok: test intentionally leaves background threads "
        "running (opts out of the per-test thread-leak guard)")
    config.addinivalue_line(
        "markers",
        "slow: long-running schedule (multi-seed chaos sweeps, minutes of "
        "fault injection) — excluded from tier-1 (`-m 'not slow'`)")
    config.addinivalue_line(
        "markers",
        "fd_leak_ok: test intentionally leaves sockets/pipes open "
        "(opts out of the per-test fd-leak guard)")


@pytest.fixture(autouse=True)
def _thread_leak_guard(request):
    """After each test, no NEW non-daemon thread may survive: a leaked
    non-daemon thread blocks interpreter exit (the process-level analog is
    the leak-police below).  Daemon threads get a short grace too, purely
    to keep one test's stragglers from being blamed on the next test's
    baseline.  Opt out with @pytest.mark.thread_leak_ok for tests that
    intentionally background work."""
    import threading
    import time

    if request.node.get_closest_marker("thread_leak_ok"):
        yield
        return
    # snapshot thread OBJECTS, not idents: CPython recycles idents after a
    # thread exits, which would let a leaked thread hide behind a baseline
    # thread's recycled id
    before = set(threading.enumerate())
    yield
    def new_nondaemon():
        return [th for th in threading.enumerate()
                if th not in before and not th.daemon and th.is_alive()]
    deadline = time.monotonic() + 2.0
    leaked = new_nondaemon()
    while leaked and time.monotonic() < deadline:
        time.sleep(0.05)
        leaked = new_nondaemon()
    assert not leaked, (
        f"non-daemon thread(s) leaked by this test: "
        f"{[th.name for th in leaked]} — join them or mark the test "
        f"thread_leak_ok")


def _socketish_fds() -> dict:
    """fd -> link target for this process's open socket/pipe fds (files
    are exempt: the interesting leak class is connections — a forgotten
    `conn.close()` on an error path holds a peer's accept slot and, at
    scale, exhausts the fd table)."""
    out = {}
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:  # non-Linux fallback: guard degrades to a no-op
        return out
    for fd in fds:
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue  # raced a close
        if target.startswith(("socket:", "pipe:")):
            out[int(fd)] = target
    return out


@pytest.fixture(autouse=True)
def _fd_leak_guard(request):
    """Socket/pipe twin of the thread-leak guard: after each test, no NEW
    socket or pipe fd may survive.  The KTPU012 lint pass keeps I/O behind
    faultline sites so chaos can sever it; this guard keeps the cleanup
    half honest — an error path that drops a connection object without
    close() passes the test that exercised it and poisons the suite's fd
    table instead.  Grace + gc.collect() because CPython closes
    refcount-dropped sockets immediately but cycle-held ones only at
    collection, and server worker threads may hold a peer fd for a beat
    while winding down.  Opt out with @pytest.mark.fd_leak_ok (and
    thread_leak_ok tests skip too: a deliberately-leaked thread owns its
    connections).  A surviving fd is only blamed on the test when NONE of
    the test's new threads are still alive: the suite tolerates daemon
    stragglers (watch handlers blocked until their next heartbeat), and
    a straggler owns its connection — the leak class this guard exists
    for is the ORPHANED socket, held by nothing but a dropped reference
    or leaked global state."""
    import gc
    import threading
    import time

    if (request.node.get_closest_marker("fd_leak_ok")
            or request.node.get_closest_marker("thread_leak_ok")):
        yield
        return
    before_threads = set(threading.enumerate())
    before = _socketish_fds()
    yield
    # fd numbers are recycled, so compare (fd, inode-target) pairs: a new
    # socket on a reused fd number must not hide behind the old snapshot
    def new_fds():
        return {fd: tgt for fd, tgt in _socketish_fds().items()
                if before.get(fd) != tgt}
    def threads_winding_down():
        return any(th.is_alive() for th in threading.enumerate()
                   if th not in before_threads)
    leaked = new_fds()
    if leaked and threads_winding_down():
        return  # a live thread owns it; the thread-leak guard arbitrates
    deadline = time.monotonic() + 2.0
    while leaked and time.monotonic() < deadline:
        gc.collect()
        time.sleep(0.05)
        leaked = new_fds()
        if leaked and threads_winding_down():
            return
    assert not leaked, (
        f"socket/pipe fd(s) leaked by this test: "
        f"{sorted(leaked.items())} — close them or mark the test "
        f"fd_leak_ok")


@pytest.fixture(scope="session", autouse=True)
def _leak_police():
    """Teardown runs after every test and fixture has finalized; raising
    here fails the whole run (a sessionfinish hook can only print — its
    exitstatus mutation is not honored)."""
    import time
    import uuid

    pre = _ktpu_procs()
    if pre:
        print(f"\n[leak-police] WARNING: {len(pre)} framework process(es) "
              f"already running before this suite (not ours; only "
              f"marker-carrying descendants can fail this run):",
              file=sys.stderr)
        for pid, cmd in pre.items():
            print(f"  pid {pid}: {cmd[:120]}", file=sys.stderr)
    # every child this pytest run spawns (directly or transitively)
    # inherits the marker via os.environ; /proc/<pid>/environ keeps it
    # even after an orphan is re-parented to init
    marker = f"KTPU_LEAKPOLICE={uuid.uuid4().hex}"
    os.environ["KTPU_LEAKPOLICE"] = marker.split("=", 1)[1]
    yield
    leaked = {}
    for _ in range(20):  # grace: SIGKILLed children may take a beat to reap
        leaked = _ktpu_procs(marker)
        if not leaked:
            return
        time.sleep(0.25)
    lines = "\n".join(f"  pid {p}: {c[:120]}" for p, c in leaked.items())
    raise RuntimeError(
        f"[leak-police] {len(leaked)} framework process(es) outlived the "
        f"suite:\n{lines}")
