import os
import sys

# Force a deterministic 8-device virtual CPU mesh for all JAX-touching tests:
# multi-chip sharding is validated on virtual devices (the driver separately
# dry-runs the multichip path), single-real-chip runs happen only in bench.py.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
