import os
import sys

# Force a deterministic 8-device virtual CPU mesh for all JAX-touching tests:
# multi-chip sharding is validated on virtual devices (the driver separately
# dry-runs the multichip path), single-real-chip runs happen only in bench.py.
#
# Note: this image registers the real-TPU "axon" platform from a
# sitecustomize hook that overrides the JAX_PLATFORMS env var, so the env
# var alone is not enough — we must also flip jax.config after import
# (config wins over the boot-time registration).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
