import os
import sys

# Force a deterministic 8-device virtual CPU mesh for all JAX-touching tests:
# multi-chip sharding is validated on virtual devices (the driver separately
# dry-runs the multichip path), single-real-chip runs happen only in bench.py.
#
# Note: this image registers the real-TPU "axon" platform from a
# sitecustomize hook that overrides the JAX_PLATFORMS env var, so the env
# var alone is not enough — we must also flip jax.config after import
# (config wins over the boot-time registration).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Runtime lock sanitizer (utils/locksan): every control-plane lock created
# after this point (and in every spawned server subprocess, via env
# inheritance) checks lock-order cycles and hold-time budgets.  setdefault
# so `KTPU_LOCKSAN=0 pytest ...` can switch it off for A/B timing runs.
os.environ.setdefault("KTPU_LOCKSAN", "1")

# Shared-object mutation sanitizer (utils/mutsan): informer caches and the
# apiserver watch cache hand out freezing proxies — an in-place mutation of
# a shared snapshot raises SharedObjectMutationError at the mutation site
# instead of silently corrupting cached state/serialized bytes.  setdefault
# so `KTPU_MUTSAN=0 pytest ...` can A/B a suspected sanitizer-induced
# failure, exactly like KTPU_LOCKSAN above.
os.environ.setdefault("KTPU_MUTSAN", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------- leak police
#
# Round 4 left ten leaked store/apiserver pairs on the box (fixture setup
# failures skipped the post-yield teardown), and those stragglers poisoned
# every later benchmark.  The suite now polices itself: any framework
# process that appears during the run and survives it FAILS the session.

def _ktpu_procs(marker: str = "") -> dict:
    """pid -> cmdline of leak suspects.

    Without a marker (session-start warning): framework processes by
    cmdline (`-m kubernetes1_tpu` / the native `bin/ktpu-*`).

    With a marker (session-end check): ANY process whose ENVIRON carries
    it — i.e. every descendant of this pytest run, even after
    re-parenting.  Matching by marker alone matters: a leaked pod
    CONTAINER runs an arbitrary command (a `python -c http.server` from
    the port-forward test leaked exactly this way) and would slip a
    cmdline filter, while a concurrent session's processes can never
    carry our marker and so can never fail our run."""
    out = {}
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace").replace("\0", " ")
        except OSError:
            continue
        if marker:
            try:
                with open(f"/proc/{pid}/environ", "rb") as f:
                    if marker.encode() not in f.read():
                        continue
            except OSError:
                continue
        elif "-m kubernetes1_tpu" not in cmd and "bin/ktpu-" not in cmd:
            continue
        out[int(pid)] = cmd.strip()
    return out


import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "thread_leak_ok: test intentionally leaves background threads "
        "running (opts out of the per-test thread-leak guard)")
    config.addinivalue_line(
        "markers",
        "slow: long-running schedule (multi-seed chaos sweeps, minutes of "
        "fault injection) — excluded from tier-1 (`-m 'not slow'`)")


@pytest.fixture(autouse=True)
def _thread_leak_guard(request):
    """After each test, no NEW non-daemon thread may survive: a leaked
    non-daemon thread blocks interpreter exit (the process-level analog is
    the leak-police below).  Daemon threads get a short grace too, purely
    to keep one test's stragglers from being blamed on the next test's
    baseline.  Opt out with @pytest.mark.thread_leak_ok for tests that
    intentionally background work."""
    import threading
    import time

    if request.node.get_closest_marker("thread_leak_ok"):
        yield
        return
    # snapshot thread OBJECTS, not idents: CPython recycles idents after a
    # thread exits, which would let a leaked thread hide behind a baseline
    # thread's recycled id
    before = set(threading.enumerate())
    yield
    def new_nondaemon():
        return [th for th in threading.enumerate()
                if th not in before and not th.daemon and th.is_alive()]
    deadline = time.monotonic() + 2.0
    leaked = new_nondaemon()
    while leaked and time.monotonic() < deadline:
        time.sleep(0.05)
        leaked = new_nondaemon()
    assert not leaked, (
        f"non-daemon thread(s) leaked by this test: "
        f"{[th.name for th in leaked]} — join them or mark the test "
        f"thread_leak_ok")


@pytest.fixture(scope="session", autouse=True)
def _leak_police():
    """Teardown runs after every test and fixture has finalized; raising
    here fails the whole run (a sessionfinish hook can only print — its
    exitstatus mutation is not honored)."""
    import time
    import uuid

    pre = _ktpu_procs()
    if pre:
        print(f"\n[leak-police] WARNING: {len(pre)} framework process(es) "
              f"already running before this suite (not ours; only "
              f"marker-carrying descendants can fail this run):",
              file=sys.stderr)
        for pid, cmd in pre.items():
            print(f"  pid {pid}: {cmd[:120]}", file=sys.stderr)
    # every child this pytest run spawns (directly or transitively)
    # inherits the marker via os.environ; /proc/<pid>/environ keeps it
    # even after an orphan is re-parented to init
    marker = f"KTPU_LEAKPOLICE={uuid.uuid4().hex}"
    os.environ["KTPU_LEAKPOLICE"] = marker.split("=", 1)[1]
    yield
    leaked = {}
    for _ in range(20):  # grace: SIGKILLed children may take a beat to reap
        leaked = _ktpu_procs(marker)
        if not leaked:
            return
        time.sleep(0.25)
    lines = "\n".join(f"  pid {p}: {c[:120]}" for p, c in leaked.items())
    raise RuntimeError(
        f"[leak-police] {len(leaked)} framework process(es) outlived the "
        f"suite:\n{lines}")
