"""racesweep harness tests: the tier-1 smoke proves every scenario runs
green under the schedsan sanitizer with invariants armed (2 seeds); the
slow tier sweeps the full default seed set.  Red-path tests pin the
verdict artifact contract: a failing scenario must ship the reproducing
seed, a replay command line, and the flight-recorder timelines."""

import pytest

from scripts.racesweep import SCENARIOS, run_race_schedule, run_scenario

SMOKE_SEEDS = [7, 1729]
FULL_SEEDS = [1, 7, 42, 1729, 9000]


class TestRaceSweepSmoke:
    @pytest.mark.parametrize("seed", SMOKE_SEEDS)
    def test_all_scenarios_green(self, seed):
        v = run_race_schedule(seed)
        assert v["ok"], v
        assert v["schedsan_seed"] == seed
        assert set(v["scenarios"]) == set(SCENARIOS)
        # every scenario did real work under the schedule
        for name, r in v["scenarios"].items():
            assert r["acked"] > 0, (name, r)

    def test_sanitizer_deactivated_after_run(self):
        """Arming is scoped to the scenario: a sweep that left probes
        force-armed would hand every later test in the session an
        accruing revision ledger it never asked for."""
        import os

        from kubernetes1_tpu.utils import invariants, schedsan

        run_scenario("bind", 7)
        assert not schedsan.active()
        if not os.environ.get(invariants.ENV_VAR):
            assert not invariants.armed()


class TestRedVerdictArtifact:
    def test_assertion_becomes_red_verdict_with_replay(self, monkeypatch):
        def boom(seed):
            raise AssertionError("synthetic race")

        monkeypatch.setitem(SCENARIOS, "boom", boom)
        v = run_scenario("boom", 42)
        assert v["ok"] is False
        assert "synthetic race" in v["error"]
        assert "KTPU_SCHEDSAN=42" in v["replay"]
        assert "flightrecorder" in v

    def test_invariant_violation_carries_probe_artifact(self, monkeypatch):
        from kubernetes1_tpu.utils import invariants

        def trip(seed):
            invariants.rev_monotonic("race.test", "s", 5)
            invariants.rev_monotonic("race.test", "s", 4)

        monkeypatch.setitem(SCENARIOS, "trip", trip)
        v = run_scenario("trip", 9000)
        assert v["ok"] is False
        assert v.get("invariant") is True
        assert "race.test" in v["error"]
        assert "9000" in v["error"]  # the reproducing seed rides in-band
        assert "flightrecorder" in v

    def test_failed_scenario_folds_into_schedule_verdict(self, monkeypatch):
        def boom(seed):
            raise AssertionError("synthetic race")

        monkeypatch.setitem(SCENARIOS, "boom", boom)
        v = run_race_schedule(1, scenarios=["bind", "boom"])
        assert v["ok"] is False
        assert "boom" in v["error"]
        assert v["scenarios"]["bind"]["ok"] is True


@pytest.mark.slow
class TestRaceSweepFull:
    @pytest.mark.parametrize("seed", FULL_SEEDS)
    def test_full_seed_sweep(self, seed):
        v = run_race_schedule(seed)
        assert v["ok"], v
