"""AuthN/AuthZ tests: bearer-token authentication (static, service-account,
certificate), RBAC evaluation, node isolation, and the audit trail — the
reference's authn/authz stack (apiserver/pkg/authentication, registry/rbac,
node authorizer) exercised over real HTTP."""

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers.certificates import issue_certificate
from kubernetes1_tpu.controllers.serviceaccount import sign_token
from kubernetes1_tpu.machinery import ApiError, Forbidden, Unauthorized


@pytest.fixture()
def rbac_master():
    audit = []
    master = Master(
        authorization_mode="Node,RBAC",
        static_tokens={
            "admin-tok": ("system:admin", ["system:masters"]),
            "alice-tok": ("alice", []),
            "bob-tok": ("bob", ["dev-team"]),
        },
        audit_log=audit,
    ).start()
    yield master, audit
    master.stop()


def admin(master):
    return Clientset(master.url, token="admin-tok")


def simple_pod(name, node=""):
    pod = t.Pod()
    pod.metadata.name = name
    pod.spec.containers = [t.Container(name="c", image="x", command=["r"])]
    if node:
        pod.spec.node_name = node
    return pod


class TestAuthn:
    def test_invalid_token_401(self, rbac_master):
        master, _ = rbac_master
        cs = Clientset(master.url, token="bogus")
        with pytest.raises(Unauthorized):
            cs.pods.list()
        cs.close()

    def test_anonymous_is_forbidden_in_rbac_mode(self, rbac_master):
        master, _ = rbac_master
        cs = Clientset(master.url)
        with pytest.raises(Forbidden, match="system:anonymous"):
            cs.pods.list()
        cs.close()

    def test_service_account_token_authenticates(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        sa = t.ServiceAccount()
        sa.metadata.name = "builder"
        sa = acs.serviceaccounts.create(sa, "default")
        sa_token = sign_token("ktpu-sa-key", "default", "builder", sa.metadata.uid)
        cs = Clientset(master.url, token=sa_token)
        # authenticated, but no binding yet -> 403 mentioning the SA username
        with pytest.raises(Forbidden, match="system:serviceaccount:default:builder"):
            cs.pods.list()
        cs.close()
        acs.close()

    def test_deleted_service_account_token_is_revoked(self, rbac_master):
        """ADVICE r1: a signed token must die with its ServiceAccount — the
        authenticator re-validates existence and uid, so delete/recreate
        revokes previously issued credentials."""
        master, _ = rbac_master
        acs = admin(master)
        sa = t.ServiceAccount()
        sa.metadata.name = "worker"
        sa = acs.serviceaccounts.create(sa, "default")
        token = sign_token("ktpu-sa-key", "default", "worker", sa.metadata.uid)
        cs = Clientset(master.url, token=token)
        with pytest.raises(Forbidden):  # authenticates; RBAC denies
            cs.pods.list()
        acs.serviceaccounts.delete("worker", "default")
        with pytest.raises(Unauthorized):  # token no longer authenticates
            cs.pods.list()
        # recreating the SA mints a new uid; the old token stays dead
        sa2 = t.ServiceAccount()
        sa2.metadata.name = "worker"
        acs.serviceaccounts.create(sa2, "default")
        with pytest.raises(Unauthorized):
            cs.pods.list()
        cs.close()
        acs.close()

    def test_certificate_credential_authenticates(self, rbac_master):
        master, _ = rbac_master
        cert = issue_certificate(
            "ktpu-ca-key", "system:node:n1", "req", groups=["system:nodes"]
        )
        cs = Clientset(master.url, token=cert)
        pods, _ = cs.pods.list()  # node authorizer grants reads
        assert pods == []
        cs.close()


class TestRBAC:
    def test_role_binding_grants_namespaced_access(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        role = t.Role(rules=[t.PolicyRule(verbs=["get", "list", "create"],
                                          resources=["pods"])])
        role.metadata.name = "pod-worker"
        role.metadata.namespace = "default"
        acs.roles.create(role)
        rb = t.RoleBinding(
            subjects=[t.Subject(kind="User", name="alice")],
            role_ref=t.RoleRef(kind="Role", name="pod-worker"),
        )
        rb.metadata.name = "alice-pods"
        rb.metadata.namespace = "default"
        acs.rolebindings.create(rb)

        alice = Clientset(master.url, token="alice-tok")
        alice.pods.create(simple_pod("mine"))
        assert alice.pods.get("mine").metadata.name == "mine"
        # not granted: delete
        with pytest.raises(Forbidden):
            alice.pods.delete("mine")
        # not granted: other namespaces
        with pytest.raises(Forbidden):
            alice.pods.list(namespace="kube-system")
        alice.close()
        acs.close()

    def test_cluster_role_binding_grants_group_access(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        cr = t.ClusterRole(rules=[t.PolicyRule(verbs=["*"], resources=["nodes"])])
        cr.metadata.name = "node-admin"
        acs.clusterroles.create(cr)
        crb = t.ClusterRoleBinding(
            subjects=[t.Subject(kind="Group", name="dev-team")],
            role_ref=t.RoleRef(kind="ClusterRole", name="node-admin"),
        )
        crb.metadata.name = "devs-nodes"
        acs.clusterrolebindings.create(crb)

        bob = Clientset(master.url, token="bob-tok")
        nodes, _ = bob.nodes.list()
        assert nodes == []
        with pytest.raises(Forbidden):
            bob.pods.list()
        bob.close()
        acs.close()

    def test_resource_names_restriction(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        acs.pods.create(simple_pod("allowed"))
        acs.pods.create(simple_pod("denied"))
        role = t.Role(rules=[t.PolicyRule(verbs=["get"], resources=["pods"],
                                          resource_names=["allowed"])])
        role.metadata.name = "one-pod"
        role.metadata.namespace = "default"
        acs.roles.create(role)
        rb = t.RoleBinding(
            subjects=[t.Subject(kind="User", name="alice")],
            role_ref=t.RoleRef(kind="Role", name="one-pod"),
        )
        rb.metadata.name = "alice-one"
        rb.metadata.namespace = "default"
        acs.rolebindings.create(rb)

        alice = Clientset(master.url, token="alice-tok")
        assert alice.pods.get("allowed").metadata.name == "allowed"
        with pytest.raises(Forbidden):
            alice.pods.get("denied")
        alice.close()
        acs.close()


class TestNodeAuthorizer:
    def _node_cs(self, master, node):
        cert = issue_certificate(
            "ktpu-ca-key", f"system:node:{node}", "req", groups=["system:nodes"]
        )
        return Clientset(master.url, token=cert)

    def test_node_updates_own_node_only(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        for n in ("n1", "n2"):
            node = t.Node()
            node.metadata.name = n
            acs.nodes.create(node)

        n1 = self._node_cs(master, "n1")
        mine = n1.nodes.get("n1", "")
        mine.status.capacity = {"cpu": "8"}
        n1.nodes.update_status(mine)  # allowed

        other = n1.nodes.get("n2", "")
        with pytest.raises(Forbidden):
            n1.nodes.update_status(other)
        n1.close()
        acs.close()

    def test_node_updates_only_pods_bound_to_it(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        acs.pods.create(simple_pod("on-n1", node="n1"))
        acs.pods.create(simple_pod("on-n2", node="n2"))

        n1 = self._node_cs(master, "n1")
        p = n1.pods.get("on-n1")
        p.status.phase = t.POD_RUNNING
        n1.pods.update_status(p)  # its own pod

        q = n1.pods.get("on-n2")
        q.status.phase = t.POD_RUNNING
        with pytest.raises(Forbidden):
            n1.pods.update_status(q)
        n1.close()
        acs.close()


class TestNodeRestriction:
    """ADVICE r1 (high): the node authorizer's mirror-pod allowance must be
    paired with NodeRestriction admission (ref: plugin/pkg/admission/
    noderestriction/admission.go:159-164) or a compromised kubelet can create
    a pod that mounts any secret and then read it via _pod_references."""

    def _node_cs(self, master, node):
        cert = issue_certificate(
            "ktpu-ca-key", f"system:node:{node}", "req", groups=["system:nodes"]
        )
        return Clientset(master.url, token=cert)

    def test_node_cannot_create_secret_mounting_pod(self, rbac_master):
        master, _ = rbac_master
        acs = admin(master)
        s = t.Secret(data={"k": "top-secret"})
        s.metadata.name = "cluster-secret"
        acs.secrets.create(s)

        n1 = self._node_cs(master, "n1")
        evil = simple_pod("evil", node="n1")
        evil.metadata.annotations[t.STATIC_POD_ANNOTATION] = "true"
        evil.spec.volumes = [
            t.Volume(name="v",
                     secret=t.SecretVolumeSource(secret_name="cluster-secret"))
        ]
        with pytest.raises(Forbidden, match="may not reference secrets"):
            n1.pods.create(evil)
        # ...and therefore the secret stays unreadable
        with pytest.raises(Forbidden):
            n1.secrets.get("cluster-secret")
        n1.close()
        acs.close()

    def test_node_can_only_create_mirror_pods_bound_to_itself(self, rbac_master):
        master, _ = rbac_master
        n1 = self._node_cs(master, "n1")
        plain = simple_pod("not-mirror", node="n1")
        with pytest.raises(Forbidden, match="mirror"):
            n1.pods.create(plain)

        foreign = simple_pod("foreign", node="n2")
        foreign.metadata.annotations[t.STATIC_POD_ANNOTATION] = "true"
        with pytest.raises(Forbidden, match="bound to itself"):
            n1.pods.create(foreign)

        ok = simple_pod("mirror-ok", node="n1")
        ok.metadata.annotations[t.STATIC_POD_ANNOTATION] = "true"
        created = n1.pods.create(ok)
        assert created.spec.node_name == "n1"
        n1.close()

    def test_node_cannot_patch_secret_volume_into_own_pod(self, rbac_master):
        """Create-clean-then-patch-in-a-secret must not re-open the
        escalation: content checks run on UPDATE/PATCH too."""
        master, _ = rbac_master
        acs = admin(master)
        s = t.Secret(data={"k": "v"})
        s.metadata.name = "cluster-secret"
        acs.secrets.create(s)

        n1 = self._node_cs(master, "n1")
        clean = simple_pod("clean-mirror", node="n1")
        clean.metadata.annotations[t.STATIC_POD_ANNOTATION] = "true"
        n1.pods.create(clean)
        with pytest.raises(Forbidden, match="may not reference"):
            n1.pods.patch(
                "clean-mirror",
                {"spec": {"volumes": [
                    {"name": "v", "secret": {"secretName": "cluster-secret"}}
                ]}},
            )
        with pytest.raises(Forbidden):
            n1.secrets.get("cluster-secret")
        n1.close()
        acs.close()

    def test_node_cannot_create_other_node_object(self, rbac_master):
        master, _ = rbac_master
        n1 = self._node_cs(master, "n1")
        other = t.Node()
        other.metadata.name = "n2"
        with pytest.raises(Forbidden, match="its own Node"):
            n1.nodes.create(other)
        mine = t.Node()
        mine.metadata.name = "n1"
        n1.nodes.create(mine)  # self-registration stays allowed
        n1.close()


class TestCSRImmutability:
    def test_csr_spec_and_creator_identity_frozen_after_create(self, rbac_master):
        """ADVICE r1: spec.username and the IdentityStamp annotations must be
        immutable after create, else update/patch rewrites them and the
        auto-approver mints a credential for a foreign node identity."""
        from kubernetes1_tpu.apiserver.admission import CREATED_BY_ANNOTATION

        master, _ = rbac_master
        acs = admin(master)
        csr = t.CertificateSigningRequest()
        csr.metadata.name = "frozen"
        csr.spec.request = "r"
        csr.spec.username = "system:node:n1"
        csr.spec.groups = ["system:nodes"]
        created = acs.certificatesigningrequests.create(csr)
        assert created.metadata.annotations[CREATED_BY_ANNOTATION] == "system:admin"

        created.spec.username = "system:node:other"
        created.metadata.annotations[CREATED_BY_ANNOTATION] = "system:node:other"
        updated = acs.certificatesigningrequests.update(created)
        assert updated.spec.username == "system:node:n1"
        assert updated.metadata.annotations[CREATED_BY_ANNOTATION] == "system:admin"

        patched = acs.certificatesigningrequests.patch(
            "frozen",
            {"spec": {"username": "system:node:other"},
             "metadata": {"annotations": {CREATED_BY_ANNOTATION: "hacker"}}},
            namespace="",
        )
        assert patched.spec.username == "system:node:n1"
        assert patched.metadata.annotations[CREATED_BY_ANNOTATION] == "system:admin"
        acs.close()


class TestCSREscalation:
    def test_node_csr_with_extra_groups_not_auto_approved(self, rbac_master):
        """A node CSR smuggling system:masters into spec.groups must wait for
        manual approval — auto-approving it would hand a kubelet cluster-admin."""
        import time

        from kubernetes1_tpu.client import InformerFactory
        from kubernetes1_tpu.controllers.certificates import CertificateController

        master, _ = rbac_master
        acs = admin(master)
        factory = InformerFactory(acs)
        ctl = CertificateController(acs, factory)
        ctl.setup()
        factory.start_all()
        factory.wait_for_sync()
        ctl.start_workers()
        try:
            csr = t.CertificateSigningRequest()
            csr.metadata.name = "sneaky"
            csr.spec.request = "r"
            csr.spec.username = "system:node:evil"
            csr.spec.groups = ["system:nodes", "system:masters"]
            acs.certificatesigningrequests.create(csr)
            time.sleep(1.0)
            got = acs.certificatesigningrequests.get("sneaky", "")
            assert not got.status.certificate
            assert not any(c.type == "Approved" for c in got.status.conditions)
        finally:
            ctl.stop()
            factory.stop_all()
            acs.close()


class TestNodeSecretsScoping:
    def test_node_reads_only_referenced_secrets(self, rbac_master):
        """A kubelet may GET a secret only when a pod bound to it mounts
        that secret; cluster-wide secret list/get is denied (the upstream
        node-authorizer graph posture)."""
        master, _ = rbac_master
        acs = admin(master)
        for name in ("mounted", "unrelated"):
            s = t.Secret(data={"k": "v"})
            s.metadata.name = name
            acs.secrets.create(s)
        pod = simple_pod("consumer", node="n1")
        pod.spec.volumes = [
            t.Volume(name="v", secret=t.SecretVolumeSource(secret_name="mounted"))
        ]
        acs.pods.create(pod)

        cert = issue_certificate(
            "ktpu-ca-key", "system:node:n1", "req", groups=["system:nodes"]
        )
        n1 = Clientset(master.url, token=cert)
        assert n1.secrets.get("mounted").data["k"] == "v"
        with pytest.raises(Forbidden):
            n1.secrets.get("unrelated")
        with pytest.raises(Forbidden):
            n1.secrets.list(namespace="default")
        n1.close()
        acs.close()


class TestCSRImpersonation:
    def test_csr_for_foreign_node_identity_not_auto_approved(self, rbac_master):
        """spec.username is client-controlled: a CSR whose authenticated
        creator is not that identity (nor a bootstrapper/admin) must wait
        for manual approval."""
        import time

        from kubernetes1_tpu.client import InformerFactory
        from kubernetes1_tpu.controllers.certificates import CertificateController

        master, _ = rbac_master
        acs = admin(master)
        factory = InformerFactory(acs)
        ctl = CertificateController(acs, factory)
        ctl.setup()
        factory.start_all()
        factory.wait_for_sync()
        ctl.start_workers()
        try:
            # n1 requests a credential for n2's identity
            cert = issue_certificate(
                "ktpu-ca-key", "system:node:n1", "r", groups=["system:nodes"]
            )
            n1 = Clientset(master.url, token=cert)
            csr = t.CertificateSigningRequest()
            csr.metadata.name = "impersonation"
            csr.spec.request = "r"
            csr.spec.username = "system:node:n2"
            csr.spec.groups = ["system:nodes"]
            created = n1.certificatesigningrequests.create(csr)
            # creator identity was stamped server-side and is not the target
            assert created.metadata.annotations["ktpu.io/created-by"] == "system:node:n1"
            time.sleep(1.0)
            got = acs.certificatesigningrequests.get("impersonation", "")
            assert not got.status.certificate
            assert not any(c.type == "Approved" for c in got.status.conditions)

            # the node renewing its OWN identity is auto-approved
            own = t.CertificateSigningRequest()
            own.metadata.name = "renewal"
            own.spec.request = "r2"
            own.spec.username = "system:node:n1"
            own.spec.groups = ["system:nodes"]
            n1.certificatesigningrequests.create(own)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if acs.certificatesigningrequests.get("renewal", "").status.certificate:
                    break
                time.sleep(0.1)
            assert acs.certificatesigningrequests.get("renewal", "").status.certificate
            n1.close()
        finally:
            ctl.stop()
            factory.stop_all()
            acs.close()


class TestAudit:
    def test_mutations_carry_user_identity(self, rbac_master):
        master, audit = rbac_master
        acs = admin(master)
        acs.pods.create(simple_pod("audited"))
        acs.pods.delete("audited")
        entries = [e for e in audit if e["name"] == "audited"]
        assert {e["verb"] for e in entries} >= {"create", "delete"}
        assert all(e["user"] == "system:admin" for e in entries)
        acs.close()


class TestLegacyTokenMode:
    def test_shared_token_still_works(self):
        master = Master(token="s3cret").start()
        cs = Clientset(master.url, token="s3cret")
        assert cs.pods.list()[0] == []
        bad = Clientset(master.url)
        with pytest.raises(ApiError):
            bad.pods.list()
        bad.close()
        cs.close()
        master.stop()


class TestWebhookTokenAuthn:
    """Remote TokenReview authn (ref: apiserver webhook token authenticator)."""

    def _idp(self, valid_tokens):
        import json as _json
        import threading as _th
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                review = _json.loads(self.rfile.read(n))
                tok = review.get("spec", {}).get("token", "")
                if tok in valid_tokens:
                    body = {"status": {"authenticated": True,
                                       "user": {"username": valid_tokens[tok],
                                                "groups": ["idp-users"]}}}
                else:
                    body = {"status": {"authenticated": False}}
                raw = _json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        httpd.daemon_threads = True
        _th.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, f"http://127.0.0.1:{httpd.server_address[1]}/tokenreview"

    def test_webhook_authenticates_and_rbac_applies(self):
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset
        from kubernetes1_tpu.machinery import ApiError

        httpd, url = self._idp({"idp-tok-1": "alice@corp"})
        master = Master(authorization_mode="Node,RBAC", token="admintok",
                        authentication_webhook_url=url).start()
        admin = Clientset(master.url, token="admintok")
        try:
            # grant alice read on pods via RBAC
            from kubernetes1_tpu.api import types as t

            role = t.ClusterRole()
            role.metadata.name = "pod-reader"
            role.rules = [t.PolicyRule(verbs=["get", "list"],
                                       resources=["pods"])]
            admin.clusterroles.create(role, "")
            rb = t.ClusterRoleBinding()
            rb.metadata.name = "alice-reads"
            rb.subjects = [t.Subject(kind="User", name="alice@corp")]
            rb.role_ref = t.RoleRef(kind="ClusterRole", name="pod-reader")
            admin.clusterrolebindings.create(rb, "")

            alice = Clientset(master.url, token="idp-tok-1")
            pods, _ = alice.pods.list(namespace="default")  # allowed
            assert pods == []
            try:
                alice.pods.create(__import__(
                    "tests.helpers", fromlist=["make_tpu_pod"]
                ).make_tpu_pod("nope"))
                raise AssertionError("create should be denied")
            except ApiError:
                pass
            alice.close()

            # an unknown token is rejected outright
            mallory = Clientset(master.url, token="bogus")
            try:
                mallory.pods.list(namespace="default")
                raise AssertionError("bogus token should 401/403")
            except ApiError:
                pass
            mallory.close()
        finally:
            admin.close()
            master.stop()
            httpd.shutdown()
            httpd.server_close()

    def test_webhook_result_cached(self):
        import itertools

        from kubernetes1_tpu.apiserver.auth import WebhookTokenAuthenticator

        calls = []

        class _CountingAuth(WebhookTokenAuthenticator):
            def __init__(self, url):
                clock = itertools.count()
                super().__init__(url, cache_ttl=1000.0,
                                 clock=lambda: next(clock))

        httpd, url = self._idp({"tok": "bob"})
        try:
            a = _CountingAuth(url)
            import urllib.request as _ur

            real = _ur.urlopen

            def counted(*args, **kw):
                calls.append(1)
                return real(*args, **kw)

            _ur.urlopen = counted
            try:
                assert a.authenticate("tok").name == "bob"
                assert a.authenticate("tok").name == "bob"
            finally:
                _ur.urlopen = real
            assert len(calls) == 1  # second hit served from cache
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestOIDCAuthn:
    """OIDC-style JWT authn (HS256, zero-egress JWKS stand-in)."""

    def test_valid_token_authenticates_with_issuer_prefix(self):
        from kubernetes1_tpu.apiserver.auth import (
            OIDCAuthenticator,
            mint_oidc_token,
        )

        a = OIDCAuthenticator("https://idp.corp", "ktpu", "k1")
        tok = mint_oidc_token("k1", "https://idp.corp", "ktpu", "alice",
                              groups=["dev"])
        u = a.authenticate(tok)
        assert u is not None
        assert u.name == "https://idp.corp#alice"
        assert "dev" in u.groups

    def test_rejections(self):
        from kubernetes1_tpu.apiserver.auth import (
            OIDCAuthenticator,
            mint_oidc_token,
        )

        a = OIDCAuthenticator("https://idp.corp", "ktpu", "k1")
        # wrong key (signature)
        assert a.authenticate(mint_oidc_token(
            "other", "https://idp.corp", "ktpu", "alice")) is None
        # wrong issuer
        assert a.authenticate(mint_oidc_token(
            "k1", "https://evil", "ktpu", "alice")) is None
        # wrong audience
        assert a.authenticate(mint_oidc_token(
            "k1", "https://idp.corp", "other-app", "alice")) is None
        # expired
        assert a.authenticate(mint_oidc_token(
            "k1", "https://idp.corp", "ktpu", "alice", ttl=-10)) is None
        # not a JWT
        assert a.authenticate("garbage") is None

    def test_alg_none_rejected(self):
        import base64
        import json as _json

        from kubernetes1_tpu.apiserver.auth import OIDCAuthenticator

        def b64e(b):
            return base64.urlsafe_b64encode(b).decode().rstrip("=")

        a = OIDCAuthenticator("https://idp.corp", "ktpu", "k1")
        header = b64e(_json.dumps({"alg": "none"}).encode())
        payload = b64e(_json.dumps({"iss": "https://idp.corp",
                                    "aud": "ktpu", "sub": "x",
                                    "exp": 9e12}).encode())
        assert a.authenticate(f"{header}.{payload}.") is None

    def test_end_to_end_with_rbac(self):
        from kubernetes1_tpu.api import types as t
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.apiserver.auth import mint_oidc_token
        from kubernetes1_tpu.client import Clientset
        from kubernetes1_tpu.machinery import ApiError

        master = Master(authorization_mode="Node,RBAC", token="root",
                        oidc_issuer="https://idp.corp",
                        oidc_client_id="ktpu",
                        oidc_hs256_key="sekrit").start()
        admin = Clientset(master.url, token="root")
        try:
            role = t.ClusterRole()
            role.metadata.name = "oidc-reader"
            role.rules = [t.PolicyRule(verbs=["list"], resources=["pods"])]
            admin.clusterroles.create(role, "")
            rb = t.ClusterRoleBinding()
            rb.metadata.name = "oidc-reader-b"
            rb.subjects = [t.Subject(kind="Group", name="platform-team")]
            rb.role_ref = t.RoleRef(kind="ClusterRole", name="oidc-reader")
            admin.clusterrolebindings.create(rb, "")
            tok = mint_oidc_token("sekrit", "https://idp.corp", "ktpu",
                                  "bob", groups=["platform-team"])
            bob = Clientset(master.url, token=tok)
            items, _ = bob.pods.list(namespace="default")
            assert items == []
            with pytest.raises(ApiError):
                bob.nodes.list()  # not granted
            bob.close()
        finally:
            admin.close()
            master.stop()

    def test_empty_key_refused_and_system_groups_stripped(self):
        from kubernetes1_tpu.apiserver.auth import (
            OIDCAuthenticator,
            mint_oidc_token,
        )

        with pytest.raises(ValueError):
            OIDCAuthenticator("https://idp.corp", "ktpu", "")
        a = OIDCAuthenticator("https://idp.corp", "ktpu", "k1")
        tok = mint_oidc_token("k1", "https://idp.corp", "ktpu", "mallory",
                              groups=["system:masters", "dev"])
        u = a.authenticate(tok)
        assert "system:masters" not in u.groups and "dev" in u.groups

    def test_non_dict_jwt_segments_rejected_not_crash(self):
        import base64
        import json as _json

        from kubernetes1_tpu.apiserver.auth import OIDCAuthenticator

        def b64e(b):
            return base64.urlsafe_b64encode(b).decode().rstrip("=")

        a = OIDCAuthenticator("https://idp.corp", "ktpu", "k1")
        # list header
        assert a.authenticate(f"{b64e(b'[]')}.{b64e(b'{}')}.x") is None
        # validly-signed non-dict payload
        import hashlib
        import hmac as _hm

        header = b64e(_json.dumps({"alg": "HS256"}).encode())
        payload = b64e(_json.dumps("just-a-string").encode())
        sig = b64e(_hm.new(b"k1", f"{header}.{payload}".encode(),
                           hashlib.sha256).digest())
        assert a.authenticate(f"{header}.{payload}.{sig}") is None
