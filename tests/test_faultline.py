"""Fault-injection layer + hardened-recovery units (tier-1, non-slow).

Covers the faultline injector itself (spec grammar, seeded determinism,
identity when inactive, byte-stream tearing), the recovery code it
exercises — WAL torn-tail repair on store open, the unified client retry
policy (transient-vs-terminal classification, capped full-jitter backoff,
Retry-After honoring), apiserver max-inflight overload shedding — and the
standby's flap-vs-death distinction (link flap resync ≠ promotion).

The multi-seed, multi-minute schedules live in tests/test_chaos.py under
the `slow` marker; this module keeps one short smoke schedule in tier-1.
"""

import os
import random
import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, SharedInformer
from kubernetes1_tpu.client import retry as client_retry
from kubernetes1_tpu.machinery import (
    ApiError,
    Conflict,
    NotFound,
    TooOldResourceVersion,
)
from kubernetes1_tpu.machinery.errors import TooManyRequests
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.remote import RemoteStore
from kubernetes1_tpu.storage.server import StoreServer
from kubernetes1_tpu.storage.standby import StandbyServer
from kubernetes1_tpu.utils import faultline
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.test_machinery import make_pod


@pytest.fixture(autouse=True)
def _no_leftover_injector():
    """Every test starts and ends with the injector inactive — a leaked
    schedule would make unrelated tests fail nondeterministically."""
    faultline.deactivate()
    yield
    faultline.deactivate()


def _retries(reason: str) -> int:
    return client_retry.retries_snapshot().get(reason, 0)


# ---------------------------------------------------------------- the injector


class TestSpecGrammar:
    def test_full_grammar_parses(self):
        inj = faultline.Injector(
            1,
            "client.request=drop@0.1|delay:20ms@0.5|error;"
            "repl.link=sever:0.3@0.2;"
            "wal.write=truncate@0.03")
        assert set(inj._sites) == {"client.request", "repl.link",
                                   "wal.write"}
        faults = inj._sites["client.request"].faults
        assert [f.action for f in faults] == ["drop", "delay", "error"]
        assert faults[1].param == pytest.approx(0.02)  # 20ms
        assert faults[0].prob == pytest.approx(0.1)
        assert faults[2].prob == 1.0  # default

    @pytest.mark.parametrize("spec", [
        "client.request",                 # no '='
        "client.request=explode",         # unknown action
        "client.request=drop@1.5",        # prob out of range
        "client.request=delay:xyz",       # bad duration
    ])
    def test_malformed_specs_raise_at_activation(self, spec):
        with pytest.raises(faultline.FaultSpecError):
            faultline.Injector(1, spec)

    def test_env_form(self):
        inj = faultline.activate_from_value("42:wal.write=truncate@0.5")
        assert inj.seed == 42
        assert faultline.active()
        with pytest.raises(faultline.FaultSpecError):
            faultline.activate_from_value("no-seed-spec-separator")
        with pytest.raises(faultline.FaultSpecError):
            faultline.activate_from_value("abc:wal.write=drop")

    @pytest.mark.parametrize("s, want", [
        ("20ms", 0.02), ("0.5s", 0.5), ("2", 2.0)])
    def test_duration_units(self, s, want):
        assert faultline._parse_duration(s) == pytest.approx(want)


class TestDeterminism:
    SPEC = "a=drop@0.5;b=sever@0.5"

    def _sequence(self, inj, site, n=64):
        return [inj.decide(site) for _ in range(n)]

    def test_same_seed_same_schedule(self):
        a = self._sequence(faultline.Injector(7, self.SPEC), "a")
        b = self._sequence(faultline.Injector(7, self.SPEC), "a")
        assert a == b
        assert any(d is not None for d in a)  # the schedule actually fires

    def test_different_seeds_differ(self):
        a = self._sequence(faultline.Injector(7, self.SPEC), "a")
        b = self._sequence(faultline.Injector(8, self.SPEC), "a")
        assert a != b

    def test_sites_are_independent_streams(self):
        # site a's decision sequence must not shift when site b is also
        # being exercised — per-site RNG streams, not one shared stream
        alone = self._sequence(faultline.Injector(7, self.SPEC), "a")
        inj = faultline.Injector(7, self.SPEC)
        interleaved = []
        for _ in range(64):
            interleaved.append(inj.decide("a"))
            inj.decide("b")
        assert alone == interleaved

    def test_unknown_site_never_fires(self):
        inj = faultline.Injector(7, self.SPEC)
        assert all(inj.decide("never.wired") is None for _ in range(16))


class TestIdentityWhenInactive:
    def test_check_and_filter_are_noops(self):
        assert not faultline.active()
        faultline.check("client.request")  # no raise
        data = b"x" * 1024
        out, exc = faultline.filter_bytes("wal.write", data)
        assert out is data  # not even a copy on the inactive path
        assert exc is None
        assert faultline.stats() == {}
        assert faultline.rng() is None


class TestByteTearing:
    def test_sever_writes_strict_prefix_then_errors(self):
        faultline.activate(3, "repl.link=sever@1.0")
        data = b"A" * 1000
        out, exc = faultline.filter_bytes("repl.link", data)
        assert isinstance(exc, faultline.FaultInjected)
        assert 0 < len(out) < len(data)
        assert data.startswith(out)

    def test_truncate_fraction_is_honored(self):
        faultline.activate(3, "wal.write=truncate:0.25@1.0")
        out, exc = faultline.filter_bytes("wal.write", b"B" * 1000)
        assert len(out) == 250
        assert isinstance(exc, faultline.FaultInjected)

    def test_error_keeps_no_bytes(self):
        faultline.activate(3, "wal.write=error@1.0")
        out, exc = faultline.filter_bytes("wal.write", b"C" * 10)
        assert out == b""
        assert isinstance(exc, faultline.FaultInjected)

    def test_delay_passes_all_bytes(self):
        faultline.activate(3, "wal.write=delay:1ms@1.0")
        data = b"D" * 10
        out, exc = faultline.filter_bytes("wal.write", data)
        assert out == data and exc is None

    def test_check_degrades_sever_to_drop(self):
        faultline.activate(3, "store.rpc=sever@1.0")
        with pytest.raises(faultline.FaultInjected):
            faultline.check("store.rpc")

    def test_injected_fault_is_a_connection_error(self):
        # recovery paths classify ConnectionError as transient; the
        # injector must walk through THOSE paths, not bespoke ones
        assert issubclass(faultline.FaultInjected, ConnectionError)
        assert client_retry.is_transient(faultline.FaultInjected("x"))


# ------------------------------------------------------- WAL torn-tail repair


class TestWalTornTailRepair:
    def _store(self, path, n=5):
        store = Store(global_scheme.copy(), wal_path=path)
        for i in range(n):
            store.create(f"/registry/pods/d/p{i}", make_pod(f"p{i}"))
        store.close()
        return path

    def test_torn_json_tail_truncated_and_counted(self, tmp_path):
        wal = self._store(str(tmp_path / "a.wal"))
        intact = os.path.getsize(wal)
        with open(wal, "ab") as f:  # a record cut mid-write by a crash
            f.write(Store._wal_frame(
                {"rev": 99, "type": "ADDED", "key": "/registry/pods/d/torn",
                 "obj": {}})[:20])
        reopened = Store(global_scheme.copy(), wal_path=str(wal))
        assert reopened.wal_torn_tail_repairs == 1
        assert os.path.getsize(wal) == intact  # torn suffix removed
        items, _ = reopened.list("/registry/pods/")
        assert len(items) == 5  # every acked write replayed
        reopened.close()

    def test_crc_mismatch_is_torn(self, tmp_path):
        wal = self._store(str(tmp_path / "b.wal"))
        frame = bytearray(Store._wal_frame(
            {"rev": 99, "type": "ADDED", "key": "/registry/pods/d/x",
             "obj": {}}))
        frame[-10] ^= 0x01  # bit flip INSIDE the payload: CRC catches it
        with open(wal, "ab") as f:
            f.write(bytes(frame))
        reopened = Store(global_scheme.copy(), wal_path=str(wal))
        assert reopened.wal_torn_tail_repairs == 1
        assert len(reopened.list("/registry/pods/")[0]) == 5
        reopened.close()

    def test_intact_wal_replays_without_repair(self, tmp_path):
        wal = self._store(str(tmp_path / "c.wal"))
        reopened = Store(global_scheme.copy(), wal_path=str(wal))
        assert reopened.wal_torn_tail_repairs == 0
        assert len(reopened.list("/registry/pods/")[0]) == 5
        reopened.close()

    def test_missing_final_newline_restored_before_append(self, tmp_path):
        """A crash can land after the last record's bytes but before its
        trailing newline: the record parses (CRC covers the JSON, not the
        \\n) and is acked state — but appending straight after it welds
        the next frame onto the same line, turning TWO durable records
        into one unparsable line a later replay would truncate or skip
        (regression: replay must restore the frame terminator)."""
        wal = self._store(str(tmp_path / "e.wal"))
        with open(wal, "r+b") as f:
            f.truncate(os.path.getsize(wal) - 1)  # lose only the \n
        reopened = Store(global_scheme.copy(), wal_path=str(wal))
        assert reopened.wal_torn_tail_repairs == 0  # record was durable
        assert len(reopened.list("/registry/pods/")[0]) == 5
        reopened.create("/registry/pods/d/p5", make_pod("p5"))
        reopened.close()
        again = Store(global_scheme.copy(), wal_path=str(wal))
        assert again.wal_torn_tail_repairs == 0
        assert again.wal_corrupt_records_skipped == 0
        assert len(again.list("/registry/pods/")[0]) == 6
        again.close()

    def test_legacy_bare_json_wal_replays(self, tmp_path):
        # pre-CRC WALs (bare JSON lines) must stay replayable in place
        import json

        wal = str(tmp_path / "legacy.wal")
        pod = global_scheme.encode(make_pod("old"))
        pod["metadata"]["resourceVersion"] = "1"
        with open(wal, "w") as f:
            f.write(json.dumps({"rev": 1, "type": "ADDED",
                                "key": "/registry/pods/d/old",
                                "obj": pod}) + "\n")
        store = Store(global_scheme.copy(), wal_path=wal)
        assert store.wal_torn_tail_repairs == 0
        assert store.get("/registry/pods/d/old").metadata.name == "old"
        store.close()

    def test_injected_tear_errors_writer_and_live_store_rolls_back(
            self, tmp_path):
        wal = str(tmp_path / "d.wal")
        store = Store(global_scheme.copy(), wal_path=wal)
        store.create("/registry/pods/d/ok", make_pod("ok"))
        faultline.activate(11, "wal.write=truncate@1.0")
        with pytest.raises(ApiError, match="WAL persistence failed"):
            # the torn prefix lands on disk and the writer errors (the
            # group-commit drain wraps the tear, failing every writer in
            # the batch) — no silent ack of a non-durable write
            store.create("/registry/pods/d/torn", make_pod("torn"))
        faultline.deactivate()
        # the LIVE store rolled the torn prefix back out, so records
        # committed AFTER the failure land on a clean WAL...
        assert store.wal_write_rollbacks == 1
        store.create("/registry/pods/d/later", make_pod("later"))
        store.close()
        # ...and a restart replays every acked write with no repair needed
        reopened = Store(global_scheme.copy(), wal_path=wal)
        assert reopened.wal_torn_tail_repairs == 0
        assert reopened.get("/registry/pods/d/ok").metadata.name == "ok"
        assert reopened.get("/registry/pods/d/later").metadata.name \
            == "later"
        with pytest.raises(NotFound):
            reopened.get("/registry/pods/d/torn")  # unacked: legitimately gone
        reopened.close()

    def test_midfile_damage_skipped_not_truncated(self, tmp_path):
        # garbage BETWEEN valid records is corruption, not a torn tail:
        # replay must keep the acked records after it — truncating there
        # would silently discard durable state
        wal = self._store(str(tmp_path / "e.wal"))
        size = os.path.getsize(wal)
        with open(wal, "r+b") as f:
            data = f.read()
            cut = data.index(b"\n", size // 2) + 1  # a record boundary
            f.seek(0)
            f.write(data[:cut] + b"xx-garbage-line\n" + data[cut:])
        store = Store(global_scheme.copy(), wal_path=str(wal))
        assert store.wal_corrupt_records_skipped == 1
        assert store.wal_torn_tail_repairs == 0
        assert len(store.list("/registry/pods/")[0]) == 5  # nothing lost
        assert os.path.getsize(wal) > size  # and nothing truncated
        store.close()


# ------------------------------------------------------- unified retry policy


class TestRetryPolicy:
    def test_classification(self):
        transient = [ConnectionError("x"), TimeoutError("x"),
                     faultline.FaultInjected("x"), TooManyRequests("shed"),
                     _api_error(503), _api_error(500)]
        terminal = [Conflict("c"), TooOldResourceVersion("relist"),
                    NotFound("n"), _api_error(400), ValueError("not-api")]
        assert all(client_retry.is_transient(e) for e in transient)
        assert not any(client_retry.is_transient(e) for e in terminal)

    def test_backoff_is_capped_exponential_with_full_jitter(self):
        bo = client_retry.Backoff(base=0.1, factor=2.0, cap=0.4,
                                  rng=random.Random(0))
        ceilings = []
        for _ in range(5):
            c = bo.ceiling()
            d = bo.next()
            ceilings.append(c)
            assert 0.0 <= d <= c  # full jitter: U(0, ceiling)
        assert ceilings == [pytest.approx(x)
                            for x in (0.1, 0.2, 0.4, 0.4, 0.4)]
        bo.reset()
        assert bo.ceiling() == pytest.approx(0.1)

    def test_jitter_rides_faultline_stream_when_active(self):
        def draw_four():
            faultline.activate(99, "x=drop@0.0")
            ds = [client_retry.Backoff(base=0.1).next() for _ in range(4)]
            faultline.deactivate()
            return ds

        assert draw_four() == draw_four()  # seeded: chaos sleeps replay

    def test_call_with_retries_transient_then_success(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("blip")
            return "ok"

        bo = client_retry.Backoff(base=0.001, cap=0.002)
        assert client_retry.call_with_retries(flaky, steps=4,
                                              backoff=bo) == "ok"
        assert len(calls) == 3

    def test_call_with_retries_terminal_raises_immediately(self):
        calls = []

        def conflicted():
            calls.append(1)
            raise Conflict("stale")

        with pytest.raises(Conflict):
            client_retry.call_with_retries(conflicted, steps=4)
        assert len(calls) == 1

    def test_call_with_retries_honors_retry_after_floor(self):
        calls = []

        def shed_once():
            calls.append(1)
            if len(calls) == 1:
                err = TooManyRequests("shed")
                err.retry_after = 0.15
                raise err
            return "ok"

        t0 = time.monotonic()
        bo = client_retry.Backoff(base=0.001, cap=0.002)
        assert client_retry.call_with_retries(shed_once, steps=3,
                                              backoff=bo) == "ok"
        assert time.monotonic() - t0 >= 0.15  # server's wait respected

    def test_retry_on_conflict_still_converges(self):
        calls = []

        def eventually():
            calls.append(1)
            if len(calls) < 3:
                raise Conflict("stale")
            return 42

        assert client_retry.retry_on_conflict(
            eventually, sleep=0.001) == 42
        with pytest.raises(Conflict):
            client_retry.retry_on_conflict(
                lambda: (_ for _ in ()).throw(Conflict("always")),
                steps=2, sleep=0.001)


def _api_error(code: int) -> ApiError:
    e = ApiError(f"http {code}")
    e.code = code
    return e


# -------------------------------------------------------- overload shedding


class TestOverloadShedding:
    def test_limiter_unit(self):
        from kubernetes1_tpu.apiserver.server import _InflightLimiter

        lim = _InflightLimiter(2)
        assert lim.acquire("POST") and lim.acquire("PUT")
        assert not lim.acquire("DELETE")  # third mutating: shed
        assert lim.shed_total == 1
        assert lim.acquire("GET")  # reads never shed
        assert lim.inflight("mutating") == 2
        assert lim.peak_mutating == 2
        assert 0.1 <= lim.retry_after() <= 2.0
        lim.release("POST")
        assert lim.acquire("PATCH")  # slot freed
        disabled = _InflightLimiter(0)
        assert all(disabled.acquire("POST") for _ in range(64))

    @pytest.mark.thread_leak_ok  # Master's HTTP worker threads
    def test_apiserver_sheds_mutations_with_retry_after(self):
        master = Master(max_inflight_mutating=1).start()
        cs = Clientset(master.url)
        try:
            # pin the single mutating slot, as a wedged in-flight write
            assert master.inflight.acquire("POST")
            cm = t.ConfigMap(data={"k": "v"})
            cm.metadata.name = "shed-me"
            t0 = time.monotonic()
            with pytest.raises(ApiError) as ei:
                cs.configmaps.create(cm, "default")
            # the client honored each shed's Retry-After before the final
            # surface: total wall >= the advertised waits it slept
            assert ei.value.code == 429
            ra = getattr(ei.value, "retry_after", None)
            assert ra is not None and ra > 0
            assert time.monotonic() - t0 >= ra
            shed = master.inflight.shed_total
            assert shed >= 1
            # reads keep flowing while mutations shed
            assert cs.configmaps.list(namespace="default") is not None
            assert master.inflight.shed_total == shed  # GETs never shed
            # slot freed -> the same mutation goes through
            master.inflight.release("POST")
            created = cs.configmaps.create(cm, "default")
            assert created.metadata.name == "shed-me"
            # the robustness counters are on /metrics for the scraper
            body = cs.api.request("GET", "/metrics", raw=True).decode()
            assert "ktpu_apiserver_shed_total" in body
            assert 'ktpu_apiserver_inflight{verb="mutating"}' in body
            assert "ktpu_client_retries_total" in body
        finally:
            cs.close()
            master.stop()


# --------------------------------------------- standby: link flap vs death


class TestStandbyFlapVsDeath:
    @pytest.fixture()
    def pair(self, tmp_path):
        psock = str(tmp_path / "primary.sock")
        ssock = str(tmp_path / "standby.sock")
        store = Store(global_scheme.copy(), wal_path=str(tmp_path / "p.wal"))
        primary = StoreServer(store, psock).start()
        standby = StandbyServer(psock, ssock,
                                wal_path=str(tmp_path / "s.wal"),
                                failover_grace=0.5).start()
        yield {"primary": primary, "standby": standby, "store": store,
               "psock": psock}
        standby.stop()
        primary.stop()

    @pytest.mark.thread_leak_ok  # server-side replication feed threads
    def test_link_flap_resyncs_without_promotion_then_death_promotes(
            self, pair):
        standby, primary = pair["standby"], pair["primary"]
        must_poll_until(lambda: primary._replica_acks,
                        timeout=10.0, desc="standby attached")
        rs = RemoteStore(global_scheme.copy(), pair["psock"])
        # mid-frame severs + drops on the replication link the whole time
        faultline.activate(1729, "repl.link=sever@0.2|drop@0.1")
        try:
            for i in range(12):
                rs.create(f"/registry/pods/d/flap{i}", make_pod(f"flap{i}"))
        finally:
            faultline.deactivate()
        # the consumer exited mid-frame at least once and came back by
        # resuming from its last ACKED revision (not the applied one)
        must_poll_until(lambda: standby.resyncs >= 1, timeout=15.0,
                        desc="replication session re-established")
        # a flapping link must NOT promote: the primary process is alive
        assert not standby.promoted.is_set()
        # ...and with the link healthy again the standby converges with
        # zero lost writes (the acked-cursor resume re-ships the gap)
        must_poll_until(
            lambda: (standby.store.current_revision()
                     == pair["store"].current_revision()),
            timeout=15.0, desc="standby caught up after flaps")
        assert len(standby.store.list("/registry/pods/")[0]) == 12
        rs.close()
        # death, by contrast, IS the promotion signal
        primary.stop()
        must_poll_until(standby.promoted.is_set, timeout=15.0,
                        desc="standby promoted after primary death")
        assert len(standby.store.list("/registry/pods/")[0]) == 12

    @pytest.mark.thread_leak_ok  # standby worker threads
    def test_silent_primary_death_promotes_via_hard_window(self, tmp_path):
        # a primary host that dies WITHOUT sending RST (power loss, a
        # partition black-holing SYNs) never produces the refused streak;
        # an uninterrupted all-failure window must still promote
        standby = StandbyServer(("10.255.255.1", 9),
                                str(tmp_path / "s.sock"),
                                failover_grace=0.3).start()
        try:
            must_poll_until(standby.promoted.is_set, timeout=30.0,
                            desc="promotion despite no RST ever arriving")
        finally:
            standby.stop()


class TestDurableAckPolicy:
    """repl_ack_policy="durable": a replication-gate timeout FAILS the
    answer (503, client retries) instead of acking unprotected — and
    conflict-class answers (AlreadyExists) are gated too, so a retry
    can't launder an unreplicated commit into a durable-looking ack.
    This is the policy the chaos sweep runs under; "available" (the
    default) keeps the tier-1 laggard contract and is covered by
    TestStandbyFlapVsDeath above."""

    @pytest.mark.thread_leak_ok  # server-side replication feed threads
    def test_timeout_fails_write_instead_of_unprotected_ack(self, tmp_path):
        from kubernetes1_tpu.storage.server import ReplicationUnavailable

        psock = str(tmp_path / "primary.sock")
        store = Store(global_scheme.copy(), wal_path=str(tmp_path / "p.wal"))
        primary = StoreServer(store, psock,
                              repl_ack_policy="durable").start()
        standby = StandbyServer(psock, str(tmp_path / "standby.sock"),
                                wal_path=str(tmp_path / "s.wal"),
                                failover_grace=30.0,
                                repl_ack_policy="durable").start()
        rs = RemoteStore(global_scheme.copy(), psock)
        try:
            must_poll_until(lambda: primary._replica_acks,
                            timeout=10.0, desc="standby attached")
            # healthy link: durable acks flow (and are actually protected)
            rs.create("/registry/pods/d/durable0", make_pod("durable0"))
            # standby gone after having attached: the gate must FAIL the
            # write — never ack it unprotected
            standby.stop()
            must_poll_until(lambda: not primary._replica_acks,
                            timeout=10.0, desc="replica feed detached")
            with pytest.raises(ApiError) as ei:
                rs.create("/registry/pods/d/durable1", make_pod("durable1"))
            assert ei.value.code == 503
            assert client_retry.is_transient(ei.value), \
                "durable-gate failures must be retriable by policy"
            # the commit itself landed on the primary — but the retry's
            # AlreadyExists answer proves that state, so it is gated too
            # (laundering an unreplicated commit into an ack would lose
            # it if the primary died here)
            with pytest.raises(ApiError) as ei:
                rs.create("/registry/pods/d/durable1", make_pod("durable1"))
            assert ei.value.code == 503
            assert primary.unprotected_acks == 0
            # a fresh standby reattaches and resyncs: the same retry now
            # gets the REAL answer (AlreadyExists — durably proven), and
            # new writes ack again
            standby2 = StandbyServer(psock, str(tmp_path / "standby2.sock"),
                                     wal_path=str(tmp_path / "s2.wal"),
                                     failover_grace=30.0,
                                     repl_ack_policy="durable").start()
            try:
                must_poll_until(lambda: primary._replica_acks,
                                timeout=10.0, desc="standby reattached")
                with pytest.raises(ApiError) as ei:
                    rs.create("/registry/pods/d/durable1",
                              make_pod("durable1"))
                assert ei.value.code == 409, \
                    "caught-up standby: the gated conflict answer ships"
                rs.create("/registry/pods/d/durable2", make_pod("durable2"))
                must_poll_until(
                    lambda: (standby2.store.current_revision()
                             == store.current_revision()),
                    timeout=10.0, desc="standby2 converged")
                assert primary.unprotected_acks == 0
                assert isinstance(  # wire round-trip keeps the 503 class
                    ei.value, ApiError)
            finally:
                standby2.stop()
        finally:
            rs.close()
            primary.stop()

    def test_policy_arg_validated(self, tmp_path):
        store = Store(global_scheme.copy())
        with pytest.raises(ValueError):
            StoreServer(store, str(tmp_path / "x.sock"),
                        repl_ack_policy="quorum")
        store.close()


# ------------------------------------------------------- short chaos smoke


class TestChaosSmoke:
    """One short seeded schedule in tier-1 (the multi-seed sweep with the
    primary kill is the `slow` tier in tests/test_chaos.py)."""

    @pytest.mark.thread_leak_ok  # full in-process topology
    def test_short_schedule_holds_invariants(self, tmp_path):
        from scripts.chaos import run_schedule

        v = run_schedule(7, duration=2.5, kill_primary=False,
                         tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["lost"] == []
        assert v["informer_converged"]
        assert v["revision_order_ok"]
        assert v["injected"], "schedule fired no faults at all"

    @pytest.mark.thread_leak_ok  # full in-process topology
    def test_identity_when_unset(self, tmp_path):
        # same invariant suite, injector never activated: everything
        # passes untouched and zero faults are recorded
        from scripts.chaos import run_schedule

        v = run_schedule(7, duration=1.5, kill_primary=False, spec="",
                         tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["injected"] == {}
        assert v["lost"] == []


# --------------------------------------- informer under injected faults


class TestInformerUnderFaults:
    @pytest.mark.thread_leak_ok  # Master's HTTP worker threads
    def test_watch_truncation_converges_losslessly(self):
        """Injected mid-stream watch cuts: the informer reconnects from
        the last delivered rv (counted), relists only when needed, and
        the cache ends byte-equal to the authoritative list."""
        master = Master().start()
        cs = Clientset(master.url)
        inf = SharedInformer(cs.configmaps, namespace="default")
        try:
            inf.start()
            assert inf.wait_for_sync(10.0)
            faultline.activate(5, "client.watch=drop@0.25")
            try:
                for i in range(40):
                    cm = t.ConfigMap(data={"i": str(i)})
                    cm.metadata.name = f"trunc-{i}"
                    cs.configmaps.create(cm, "default")
                    time.sleep(0.01)
                deadline = time.monotonic() + 30.0
                want = {f"trunc-{i}" for i in range(40)}
                while time.monotonic() < deadline:
                    if {o.metadata.name for o in inf.list()} >= want:
                        break
                    time.sleep(0.1)
            finally:
                faultline.deactivate()
            assert {o.metadata.name for o in inf.list()} >= want
            # the recovery paths actually ran: at least one mid-stream
            # reconnect (the drop site fires on every frame read)
            assert inf.reconnects >= 1, (inf.reconnects, inf.relists)
            assert inf.relists >= 1  # initial sync at minimum
        finally:
            inf.stop()
            cs.close()
            master.stop()
