"""Unit tests for the ktpulint passes: every pass must fire on a minimal
bad example AND stay quiet on the corresponding good one."""

import textwrap

from tools.ktpulint import lint_file


def _lint(src: str):
    return lint_file("<mem>", textwrap.dedent(src))


def _ids(src: str):
    return [f.pass_id for f in _lint(src)]


# ----------------------------------------------------------- KTPU001 (locks)

BAD_MUTATION = """
    import threading

    class C:
        def __init__(self):
            self._lock = make_lock("C._lock")
            self._items = {}

        def put(self, k, v):
            with self._lock:
                self._items[k] = v

        def drop(self, k):
            self._items.pop(k, None)  # no lock!
"""

GOOD_MUTATION = """
    import threading

    class C:
        def __init__(self):
            self._lock = make_lock("C._lock")
            self._items = {}

        def put(self, k, v):
            with self._lock:
                self._items[k] = v

        def drop(self, k):
            with self._lock:
                self._items.pop(k, None)
"""


def test_ktpu001_fires_on_unlocked_mutation():
    findings = _lint(BAD_MUTATION)
    assert [f.pass_id for f in findings] == ["KTPU001"]
    assert "_items" in findings[0].message


def test_ktpu001_quiet_on_locked_mutation():
    assert _ids(GOOD_MUTATION) == []


def test_ktpu001_init_and_locked_suffix_exempt():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")
                self._items = {}
                self._items["seed"] = 1

            def put(self, k, v):
                with self._lock:
                    self._put_locked(k, v)

            def _put_locked(self, k, v):
                self._items[k] = v
    """
    assert _ids(src) == []


def test_ktpu001_factory_locks_recognized():
    src = """
        from kubernetes1_tpu.utils import locksan

        class C:
            def __init__(self):
                self._lock = locksan.make_rlock("C._lock")
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                self._n = 0
    """
    assert _ids(src) == ["KTPU001"]


def test_ktpu001_def_line_pragma_exempts_method():
    src = BAD_MUTATION.replace(
        "def drop(self, k):",
        "def drop(self, k):  # ktpulint: ignore[KTPU001] single-threaded teardown")
    assert _ids(src) == []


# -------------------------------------------------------- KTPU002 (blocking)

def test_ktpu002_fires_on_sleep_under_lock():
    src = """
        import threading, time

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")

            def poll(self):
                with self._lock:
                    time.sleep(0.5)
    """
    ids = _ids(src)
    assert "KTPU002" in ids


def test_ktpu002_quiet_on_sleep_outside_lock():
    src = """
        import threading, time

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")
                self._n = 0

            def poll(self):
                with self._lock:
                    self._n += 1
                time.sleep(0.5)
    """
    assert _ids(src) == []


def test_ktpu002_def_line_pragma_exempts_method():
    src = """
        import threading, time

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")

            def poll(self):  # ktpulint: ignore[KTPU002] lock is private to this test helper
                with self._lock:
                    time.sleep(0.5)
    """
    assert _ids(src) == []


def test_ktpu002_fires_on_thread_join_under_lock():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")
                self._worker = threading.Thread(target=print, daemon=True)

            def stop(self):
                with self._lock:
                    self._worker.join()
    """
    assert "KTPU002" in _ids(src)


# ------------------------------------------------------ KTPU003 (exceptions)

def test_ktpu003_fires_on_bare_except():
    src = """
        def f():
            try:
                g()
            except:
                pass
    """
    assert _ids(src) == ["KTPU003"]


def test_ktpu003_fires_on_swallowed_broad_exception():
    src = """
        def f():
            try:
                g()
            except Exception:
                pass
    """
    assert _ids(src) == ["KTPU003"]


def test_ktpu003_quiet_when_narrowed_or_handled():
    src = """
        import traceback

        def f():
            try:
                g()
            except OSError:
                pass
            try:
                g()
            except Exception:
                traceback.print_exc()
            try:
                g()
            except BaseException:
                cleanup()
                raise
    """
    assert _ids(src) == []


# --------------------------------------------------------- KTPU004 (threads)

def test_ktpu004_fires_on_undaemonized_unjoined_thread():
    src = """
        import threading

        def f():
            threading.Thread(target=print).start()
    """
    assert _ids(src) == ["KTPU004"]


def test_ktpu004_quiet_on_daemon_kwarg():
    src = """
        import threading

        def f():
            threading.Thread(target=print, daemon=True).start()
    """
    assert _ids(src) == []


def test_ktpu004_quiet_on_daemon_attribute_or_join():
    src = """
        import threading

        class C:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.daemon = True
                self._t.start()
                w = threading.Thread(target=print)
                w.start()
                w.join()
    """
    assert _ids(src) == []


def test_ktpu004_annassign_handle_and_joined_collection():
    src = """
        import threading

        class C:
            def start(self):
                self._t: threading.Thread = threading.Thread(target=print)
                self._threads = []
                self._threads.append(threading.Thread(target=print))

            def stop(self):
                self._t.join()
                for th in self._threads:
                    th.join(timeout=2)
    """
    assert _ids(src) == []


def test_ktpu004_join_in_other_method_of_same_class_counts():
    src = """
        import threading

        class C:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()

            def stop(self):
                self._t.join(timeout=2)
    """
    assert _ids(src) == []


# ------------------------------------------------------- KTPU005 (wallclock)

def test_ktpu005_fires_on_time_time():
    src = """
        import time

        def deadline():
            return time.time() + 30
    """
    assert _ids(src) == ["KTPU005"]


def test_ktpu005_quiet_on_monotonic_and_pragma():
    src = """
        import time

        def deadline():
            return time.monotonic() + 30

        def stamp():
            return time.time()  # ktpulint: ignore[KTPU005] user-visible timestamp
    """
    assert _ids(src) == []


# ------------------------------------------------------- KTPU006 (iteration)

def test_ktpu006_fires_on_unlocked_iteration():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")
                self._m = {}

            def put(self, k, v):
                with self._lock:
                    self._m[k] = v

            def dump(self):
                return [v for v in self._m.values()]
    """
    assert "KTPU006" in _ids(src)


def test_ktpu006_def_line_pragma_exempts_method():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")
                self._m = {}

            def put(self, k, v):
                with self._lock:
                    self._m[k] = v

            def dump(self):  # ktpulint: ignore[KTPU006] single-threaded reporting path
                return [v for v in self._m.values()]
    """
    assert _ids(src) == []


def test_ktpu006_quiet_on_snapshot_under_lock():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")
                self._m = {}

            def put(self, k, v):
                with self._lock:
                    self._m[k] = v

            def dump(self):
                with self._lock:
                    snap = list(self._m.values())
                return [v for v in snap]
    """
    assert _ids(src) == []


# ------------------------------------------------------------------- engine

def test_only_filter_matches_finding_ids_not_registry_keys():
    """KTPU002/006 come from the pass registered as KTPU001; filtering
    must work on the emitted id."""
    import textwrap

    from tools.ktpulint import lint_file

    src = textwrap.dedent("""
        import threading, time

        class C:
            def __init__(self):
                self._lock = make_lock("C._lock")

            def poll(self):
                with self._lock:
                    time.sleep(0.5)
    """)
    findings = lint_file("<mem>", src, only=("KTPU002",))
    assert [f.pass_id for f in findings] == ["KTPU002"]
    assert lint_file("<mem>", src, only=("KTPU004",)) == []


def test_syntax_error_reported_not_raised():
    findings = _lint("def broken(:\n")
    assert [f.pass_id for f in findings] == ["KTPU000"]


def test_render_format_is_file_line_passid():
    f = _lint(BAD_MUTATION)[0]
    rendered = f.render()
    assert rendered.startswith("<mem>:")
    assert " KTPU001 " in rendered


# ----------------------------------------------------- KTPU007 (lock factory)

def test_ktpu007_fires_on_direct_lock_rlock_condition():
    src = """
        import threading

        a = threading.Lock()
        b = threading.RLock()
        c = threading.Condition()
    """
    ids = _ids(src)
    assert ids.count("KTPU007") == 3
    msgs = [f.message for f in _lint(src)]
    assert any("make_lock" in m for m in msgs)
    assert any("make_rlock" in m for m in msgs)
    assert any("make_condition" in m for m in msgs)


def test_ktpu007_quiet_on_locksan_factories():
    src = """
        from kubernetes1_tpu.utils import locksan

        a = locksan.make_lock("X._lock")
        b = locksan.make_rlock("X._rlock")
        c = locksan.make_condition(name="X._cond")
    """
    assert _ids(src) == []


def test_ktpu007_pragma_and_locksan_file_exempt():
    src = 'import threading\nL = threading.Lock()  # ktpulint: ignore[KTPU007] hot leaf\n'
    assert [f.pass_id for f in lint_file("<mem>", src)] == []
    # the factory module itself wraps the primitives and is exempt
    src2 = "import threading\nL = threading.Lock()\n"
    assert lint_file("pkg/utils/locksan.py", src2) == []
    assert [f.pass_id for f in lint_file("pkg/utils/other.py", src2)] == ["KTPU007"]


# ------------------------------------------------- KTPU008 (shared snapshots)

def test_ktpu008_informer_mutations_flagged():
    src = """
        class C:
            def setup(self):
                self.pods = self.factory.informer("pods")

            def sync(self, key):
                pod = self.pods.get(key)
                pod.status.phase = "Failed"
                pod.metadata.annotations["x"] = "y"
                pod.metadata.labels.update({"a": "b"})
                for p in self.pods.list():
                    p.spec.node_name = "n1"
    """
    assert _ids(src).count("KTPU008") == 4


def test_ktpu008_clone_sanitizes():
    src = """
        class C:
            def setup(self):
                self.pods = self.factory.informer("pods")

            def sync(self, key):
                pod = self.pods.get(key).clone()
                pod.status.phase = "Failed"
                other = self.pods.get(key)
                fresh = other.clone()
                fresh.metadata.annotations["x"] = "y"
                dc = deepcopy(self.pods.get(key))
                dc.spec.node_name = "n"
    """
    assert _ids(src) == []


def test_ktpu008_shallow_copies_keep_elements_shared():
    src = """
        class C:
            def setup(self):
                self.pods = self.factory.informer("pods")

            def sync(self, key):
                items = list(self.pods.list())
                items.append(1)          # private container: fine
                items[0].status.reason = "x"   # element: shared
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU008"]
    assert len(findings) == 1


def test_ktpu008_snapshot_and_raw_sources():
    src = """
        def f(cache, cacher):
            snap = cache.snapshot()
            for name, ni in snap.items():
                ni.pods["k"] = 1
            d = cacher.get_raw("/registry/pods/a/b")
            d["spec"]["nodeName"] = "n"
            entries, rev = cacher.list_raw("/registry/pods/")
    """
    assert _ids(src).count("KTPU008") == 2


def test_ktpu008_memo_slots_exempt():
    src = """
        def f(informer, key):
            pod = informer.get(key)
            pod._ktpu_mcpu = 500
    """
    assert _ids(src) == []


def test_ktpu008_reassignment_kills_taint():
    src = """
        def f(informer, key):
            pod = informer.get(key)
            pod = make_pod()
            pod.status.phase = "Failed"
    """
    assert _ids(src) == []


# ------------------------------------------------- KTPU009 (raw-dict schema)

def test_ktpu009_typo_flagged_and_valid_chain_quiet():
    src = """
        def f(d):
            good = d["spec"]["nodeName"]
            meta = d.get("metadata") or {}
            rv = meta.get("resourceVersion")
            bad = d["spec"]["nodename"]
            worse = (d.get("metdata") or {}).get("name")
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU009"]
    assert len(findings) == 1  # 'nodename'; 'metdata' is not an API root
    assert "nodename" in findings[0].message


def test_ktpu009_metadata_typo_below_root():
    src = """
        def f(d):
            x = (d.get("metadata") or {}).get("resourceVerison")
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU009"]
    assert len(findings) == 1
    assert "resourceVerison" in findings[0].message


def test_ktpu009_freeform_subtrees_unchecked():
    src = """
        def f(d):
            lbl = d["metadata"]["labels"]["anything-goes"]
            ann = (d.get("metadata") or {}).get("annotations", {}).get("x.y/z")
            data = d["spec"]["nodeSelector"]["my.custom/key"]
    """
    assert [f.pass_id for f in _lint(src) if f.pass_id == "KTPU009"] == []


def test_ktpu009_context_flows_through_assignment():
    src = """
        def f(d):
            spec = d.get("spec") or {}
            tmpl = spec.get("template") or {}
            labels = (tmpl.get("metadata") or {}).get("labels") or {}
            bad = spec.get("templtae")
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU009"]
    assert len(findings) == 1
    assert "templtae" in findings[0].message


# ------------------------------------------- KTPU010 (pragma justification)

def test_ktpu010_bare_pragma_flagged_and_unsuppressible():
    src = "import time\nx = time.time()  # ktpulint: ignore[KTPU005]\n"
    ids = [f.pass_id for f in lint_file("<mem>", src)]
    assert ids == ["KTPU010"]  # KTPU005 suppressed; the bare pragma is not
    src2 = "import time\nx = time.time()  # ktpulint: ignore[*]\n"
    assert [f.pass_id for f in lint_file("<mem>", src2)] == ["KTPU010"]


def test_ktpu010_justified_pragma_clean():
    src = ("import time\n"
           "x = time.time()  # ktpulint: ignore[KTPU005] user-visible stamp\n")
    assert lint_file("<mem>", src) == []


# ------------------------------------------------- CLI: JSON output+baseline

def test_finding_json_schema_and_baseline_diff():
    from tools.ktpulint.engine import Finding, diff_against_baseline

    f1 = Finding("/repo/a.py", 3, "KTPU005", "msg one")
    f2 = Finding("/repo/b.py", 9, "KTPU008", "msg two")
    assert f1.to_json("/repo") == {
        "rule": "KTPU005", "path": "a.py", "line": 3, "message": "msg one"}
    baseline = [f1.to_json("/repo")]
    # f1 is grandfathered even if its line MOVED; f2 is new
    moved = Finding("/repo/a.py", 33, "KTPU005", "msg one")
    new = diff_against_baseline([moved, f2], baseline, "/repo")
    assert [f.pass_id for f in new] == ["KTPU008"]
    # multiset: a second copy of a baselined finding still fails
    new2 = diff_against_baseline([moved, moved], baseline, "/repo")
    assert len(new2) == 1


def test_ktpu009_context_does_not_bleed_across_functions():
    """Regression: the module-scope walk must PRUNE function bodies — a
    parameter that shares a name with another function's context variable
    must not inherit that context."""
    src = """
        def a(d):
            spec = d.get("spec") or {}
            return spec

        def b(spec):
            return spec.get("anything_else")
    """
    assert [f.pass_id for f in _lint(src) if f.pass_id == "KTPU009"] == []


def test_multiple_pragmas_on_one_line_each_parse():
    """Regression: the justification group is bounded at the next '#', so
    two pragmas on one line both suppress, and a BARE second pragma is
    still caught by KTPU010 (it must not hide inside the first pragma's
    justification)."""
    from tools.ktpulint.engine import bare_pragmas, suppressed_ids

    both = "x = 1  # ktpulint: ignore[KTPU001] why  # ktpulint: ignore[KTPU002] why"
    assert suppressed_ids(both) == {"KTPU001", "KTPU002"}
    assert bare_pragmas([both], "x.py") == []
    bare_second = "x = 1  # ktpulint: ignore[KTPU001] why  # ktpulint: ignore[KTPU002]"
    assert [f.pass_id for f in bare_pragmas([bare_second], "x.py")] == ["KTPU010"]


# ------------------------------------------------- KTPU011 (obs naming)

def test_ktpu011_fires_on_unprefixed_metric_constructor():
    src = """
        from kubernetes1_tpu.utils.metrics import Counter

        requests = Counter("requests_total", "oops, no namespace")
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU011"]
    assert len(findings) == 1
    assert "requests_total" in findings[0].message


def test_ktpu011_fires_on_unprefixed_registry_method():
    src = """
        def setup(reg):
            return reg.histogram("latency_seconds")
    """
    assert [f.pass_id for f in _lint(src)] == ["KTPU011"]


def test_ktpu011_quiet_on_prefixed_names_and_foreign_counters():
    src = """
        from collections import Counter
        from kubernetes1_tpu.utils.metrics import Histogram

        chars = Counter("abcabc")  # collections.Counter: out of scope
        h = Histogram("ktpu_lag_seconds")

        def setup(reg):
            reg.counter("scheduler_schedule_attempts_total")
            reg.gauge("ktpu_queue_depth")
    """
    assert _ids(src) == []


def test_ktpu011_fires_on_ad_hoc_flightrec_kind():
    src = """
        from kubernetes1_tpu.utils import flightrec

        def f():
            flightrec.note("scheduler", "my_random_kind", shard=3)
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU011"]
    assert len(findings) == 1
    assert "my_random_kind" in findings[0].message


def test_ktpu011_quiet_on_enum_flightrec_kind():
    src = """
        from kubernetes1_tpu.utils import flightrec

        def f():
            flightrec.note("scheduler", flightrec.LEASE_STEAL, shard=3)
    """
    assert _ids(src) == []


def test_ktpu011_fires_on_keyword_name_arg():
    src = """
        from kubernetes1_tpu.utils.metrics import Histogram

        h = Histogram(name="latency_seconds", help_="no prefix, keyword")
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU011"]
    assert len(findings) == 1 and "latency_seconds" in findings[0].message


def test_ktpu011_fires_on_keyword_flightrec_kind():
    src = """
        from kubernetes1_tpu.utils import flightrec

        def f():
            flightrec.note("scheduler", kind="sneaky_kind", shard=1)
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU011"]
    assert len(findings) == 1 and "sneaky_kind" in findings[0].message


def test_ktpu011_covers_appmetrics_construction_sites():
    """Workload AppMetrics series ride the kubelet scrape pipeline into
    the fleet merge — an unprefixed workload metric collides exactly
    like an unprefixed component one, at BOTH construction shapes."""
    src = """
        from kubernetes1_tpu.obs.appmetrics import AppMetrics

        am = AppMetrics()
        am.counter("workload_requests_total")  # attr form
    """
    findings = [f for f in _lint(src) if f.pass_id == "KTPU011"]
    assert len(findings) == 1
    assert "workload_requests_total" in findings[0].message
    # classes re-exported from an appmetrics module gate like
    # utils.metrics imports
    src2 = """
        from kubernetes1_tpu.obs.appmetrics import Counter

        c = Counter("bare_name_total")
    """
    findings2 = [f for f in _lint(src2) if f.pass_id == "KTPU011"]
    assert len(findings2) == 1 and "bare_name_total" in findings2[0].message


def test_ktpu011_scorecard_requires_ktpu_slo_prefix():
    """obs/scorecard.py is the one producer of SLO verdict series: a
    plain ktpu_ prefix (fine anywhere else) is a finding THERE, so the
    scorecard's output can never shadow the series it judges."""
    src = """
        def build(reg):
            reg.counter("ktpu_good_total")  # ktpu_ but not ktpu_slo_
            reg.gauge("ktpu_slo_burn_rate")  # correct family
    """
    findings = lint_file("kubernetes1_tpu/obs/scorecard.py",
                         textwrap.dedent(src))
    findings = [f for f in findings if f.pass_id == "KTPU011"]
    assert len(findings) == 1
    assert "ktpu_slo_" in findings[0].message
    assert "ktpu_good_total" in findings[0].message
    # the same source anywhere else is clean
    assert [f.pass_id for f in lint_file(
        "kubernetes1_tpu/obs/collector.py", textwrap.dedent(src))] == []


def test_ktpu011_flightrec_attribute_kind_checked_against_enum():
    """A flightrec.X attribute kind must exist in the declared enum
    (utils/flightrec.py, parsed statically): a typo'd kind is a lint
    finding, not a runtime AttributeError in a breach path."""
    bad = """
        from kubernetes1_tpu.utils import flightrec

        def f():
            flightrec.note("scorecard", flightrec.SLO_BREACHED, slo="x")
    """
    findings = [f for f in _lint(bad) if f.pass_id == "KTPU011"]
    assert len(findings) == 1
    assert "SLO_BREACHED" in findings[0].message
    good = """
        from kubernetes1_tpu.utils import flightrec

        def f():
            flightrec.note("scorecard", flightrec.SLO_BREACH, slo="x")
            flightrec.note("mixer", flightrec.SCORECARD_PHASE, phase="mix")
    """
    assert _ids(good) == []


def test_ktpu011_quiet_on_prefixed_appmetrics_and_hpa_rescale_kind():
    src = """
        from kubernetes1_tpu.obs.appmetrics import AppMetrics
        from kubernetes1_tpu.utils import flightrec

        am = AppMetrics()
        am.gauge("ktpu_llama_qps")
        am.histogram("ktpu_llama_request_latency_seconds")

        def f():
            flightrec.note("hpa", flightrec.HPA_RESCALE, to_replicas=3)
    """
    assert _ids(src) == []


# ------------------------------------------------------ KTPU012 (io boundary)


def _lint_at(path, src):
    return lint_file(path, textwrap.dedent(src))


def test_ktpu012_fires_on_raw_dial_without_faultline():
    src = """
        import socket

        def dial(addr):
            return socket.create_connection(addr, timeout=1.0)
    """
    findings = _lint_at("kubernetes1_tpu/kubelet/x.py", src)
    assert [f.pass_id for f in findings] == ["KTPU012"]
    assert "create_connection" in findings[0].message


def test_ktpu012_fires_on_write_open_and_makefile():
    src = """
        def save(path, data, conn):
            f = conn.makefile("rwb")
            with open(path, "w") as out:
                out.write(data)
    """
    ids = [f.pass_id for f in _lint_at("kubernetes1_tpu/kubelet/x.py", src)]
    assert ids == ["KTPU012", "KTPU012"]


def test_ktpu012_quiet_when_module_references_faultline():
    src = """
        import socket
        from ..utils import faultline

        def dial(addr):
            faultline.check("x.dial")
            return socket.create_connection(addr, timeout=1.0)
    """
    assert _lint_at("kubernetes1_tpu/kubelet/x.py", src) == []


def test_ktpu012_quiet_on_read_open_and_exempt_trees():
    read_only = """
        def load(path):
            with open(path) as f:
                return f.read()
    """
    assert _lint_at("kubernetes1_tpu/kubelet/x.py", read_only) == []
    dial = """
        import socket

        def dial(addr):
            return socket.create_connection(addr)
    """
    # operator/user-side trees are outside the fault envelope
    assert _lint_at("kubernetes1_tpu/cli/x.py", dial) == []
    assert _lint_at("kubernetes1_tpu/workloads/x.py", dial) == []
    # and so is anything not under the package at all
    assert _lint_at("scripts/x.py", dial) == []


def test_ktpu012_pragma_with_justification():
    src = """
        def save(path, data):
            with open(path, "w") as f:  # ktpulint: ignore[KTPU012] bootstrap-only
                f.write(data)
    """
    assert _lint_at("kubernetes1_tpu/kubelet/x.py", src) == []


# ------------------------------------------------------ KTPU013 (sleep retry)


def test_ktpu013_fires_on_sleep_in_retry_loop():
    src = """
        import time

        def call(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(0.2)
    """
    findings = _lint(src)
    assert [f.pass_id for f in findings] == ["KTPU013"]
    assert "Backoff" in findings[0].message


def test_ktpu013_fires_on_for_loop_retry():
    src = """
        import time

        def call(fn):
            for _ in range(5):
                try:
                    return fn()
                except OSError:
                    pass
                time.sleep(0.1)
    """
    assert [f.pass_id for f in _lint(src)] == ["KTPU013"]


def test_ktpu013_quiet_on_nonretry_loop_and_sleep_zero():
    no_retry = """
        import time

        def tick():
            while True:
                time.sleep(0.5)
    """
    assert _lint(no_retry) == []
    yield_only = """
        import time

        def spin(fn):
            while True:
                try:
                    return fn()
                except OSError:
                    time.sleep(0)
    """
    assert _lint(yield_only) == []


def test_ktpu013_retry_module_itself_exempt():
    src = """
        import time

        def call(fn):
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(0.2)
    """
    assert _lint_at("kubernetes1_tpu/client/retry.py", src) == []


def test_ktpu013_pragma_with_justification():
    src = """
        import time

        def poll(fn):
            while True:
                try:
                    fn()
                except OSError:
                    pass
                time.sleep(0.5)  # ktpulint: ignore[KTPU013] fixed sampling cadence
    """
    assert _lint(src) == []


# ------------------------------------------------------- KTPU014 (lock scope)


COND_GUARDED = """
    from kubernetes1_tpu.utils import locksan

    class Cache:
        def __init__(self):
            self._cond = locksan.make_condition(name="Cache._cond")
            self._data = {{}}
            self._index = {{}}

        {method}
"""


def _lint_cond(method: str):
    return _lint(COND_GUARDED.format(method=textwrap.dedent(method).strip()
                                     .replace("\n", "\n        ")))


def test_ktpu014_fires_on_unguarded_write_to_guarded_structure():
    findings = _lint_cond("""
        def put(self, k, v):
            with self._cond:
                self._data[k] = v

        def evict(self, k):
            self._data.pop(k, None)
    """)
    # KTPU001 fires on the same write (a condition IS the class's lock);
    # this pass adds the scope story — which critical section was skipped
    got = [f for f in findings if f.pass_id == "KTPU014"]
    assert len(got) == 1
    assert "_data" in got[0].message


def test_ktpu014_quiet_when_all_writes_guarded():
    assert _lint_cond("""
        def put(self, k, v):
            with self._cond:
                self._data[k] = v
                self._index[k] = v

        def drop(self, k):
            with self._cond:
                self._data.pop(k, None)
    """) == []


def test_ktpu014_locked_suffix_method_trusted():
    # *_locked methods are called WITH the cond held by convention — the
    # same contract KTPU001 honors for lock-guarded attributes
    assert _lint_cond("""
        def put(self, k, v):
            with self._cond:
                self._data[k] = v

        def _evict_locked(self, k):
            self._data.pop(k, None)
    """) == []


def test_ktpu014_nested_function_does_not_inherit_guard():
    # a callback defined INSIDE the critical section runs later, on
    # another thread, without the cond — its writes must still be flagged
    findings = _lint_cond("""
        def put(self, k, v):
            with self._cond:
                self._data[k] = v

                def later():
                    self._data.pop(k, None)
                return later
    """)
    assert "KTPU014" in [f.pass_id for f in findings]


def test_ktpu014_quiet_without_condition_attr():
    src = """
        class Plain:
            def __init__(self):
                self._data = {}

            def put(self, k, v):
                self._data[k] = v
    """
    assert _lint(src) == []


# -------------------------------------------------- KTPU015 (event loop)

THREAD_IN_SERVING_MODULE = """
    import threading

    def serve_watch(conn):
        th = threading.Thread(target=pump, args=(conn,), daemon=True)
        th.start()
"""


def _lint_at(path: str, src: str):
    return lint_file(path, textwrap.dedent(src))


def test_ktpu015_fires_in_covered_serving_modules():
    for mod in ("apiserver/server.py", "obs/collector.py",
                "kubelet/podscrape.py", "utils/eventloop.py"):
        findings = _lint_at(f"/repo/kubernetes1_tpu/{mod}",
                            THREAD_IN_SERVING_MODULE)
        got = [f for f in findings if f.pass_id == "KTPU015"]
        assert len(got) == 1, mod
        assert "dispatcher" in got[0].message


def test_ktpu015_fires_on_timer_and_bare_thread_names():
    src = """
        from threading import Thread
        import threading

        def scrape(tgt):
            Thread(target=tgt.run, daemon=True).start()
            threading.Timer(1.0, tgt.rearm).start()
    """
    findings = _lint_at("/repo/kubernetes1_tpu/obs/collector.py", src)
    assert [f.pass_id for f in findings
            if f.pass_id == "KTPU015"] == ["KTPU015"] * 2


def test_ktpu015_quiet_outside_covered_modules():
    # the invariant is scoped to the refactored serving/scrape modules;
    # controllers and the kubelet's per-request stream pumps keep their
    # own threading idioms (KTPU004 still applies everywhere)
    for path in ("/repo/kubernetes1_tpu/controllers/job.py",
                 "/repo/kubernetes1_tpu/kubelet/server.py", "<mem>"):
        findings = _lint_at(path, THREAD_IN_SERVING_MODULE)
        assert [f.pass_id for f in findings if f.pass_id == "KTPU015"] == []


def test_ktpu015_justified_pragma_suppresses():
    src = """
        import threading

        def start_pool():
            th = threading.Thread(  # ktpulint: ignore[KTPU015] bounded worker pool slot, not per-connection
                target=work, daemon=True)
            th.start()
    """
    findings = _lint_at("/repo/kubernetes1_tpu/obs/collector.py", src)
    assert [f.pass_id for f in findings if f.pass_id == "KTPU015"] == []


# ------------------------------------------- KTPU016/017 (call-graph passes)
#
# The interprocedural passes ride tools/ktpulint/callgraph.py: these tests
# pin the resolution machinery (aliases, self-attr types, inheritance, the
# sanctioned edge cuts) and the two passes' fire/stay-quiet contracts.

from tools.ktpulint import callgraph as _callgraph  # noqa: E402


def _cg(sources: dict):
    """Findings over an in-memory multi-file graph (raw: no pragmas)."""
    return _callgraph.analyze_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})


def _cg_ids(sources: dict):
    return [f.pass_id for f in _cg(sources)]


def test_callgraph_resolves_module_alias():
    # svc reaches util.slow() only through `import util as u` — the alias
    # table must carry the edge or the blocking sleep hides behind it
    findings = _cg({
        "util.py": """
            import time

            def slow():
                time.sleep(0.5)
        """,
        "svc.py": """
            import util as u

            class S:
                def __init__(self, loop):
                    self.loop = loop

                def start(self):
                    self.loop.call_soon(self._tick)

                def _tick(self):
                    u.slow()
        """,
    })
    assert [f.pass_id for f in findings] == ["KTPU016"]
    # attributed at the blocking primitive (where the fix goes), with the
    # dispatcher-side chain in the message
    assert findings[0].path == "util.py"
    assert "slow" in findings[0].message


def test_callgraph_resolves_self_attr_method():
    # self.store's type comes from the ctor assign; .flush() must resolve
    # into Store.flush, where the blocking fsync lives
    ids = _cg_ids({"m.py": """
        import os

        class Store:
            def flush(self):
                os.fsync(3)

        class Owner:
            def __init__(self, loop):
                self.loop = loop
                self.store = Store()

            def start(self):
                self.loop.call_soon(self._commit)

            def _commit(self):
                self.store.flush()
    """})
    assert ids == ["KTPU016"]


def test_callgraph_resolves_inherited_method():
    ids = _cg_ids({"m.py": """
        import time

        class Base:
            def _drain(self):
                time.sleep(0.1)

        class Derived(Base):
            def __init__(self, loop):
                self.loop = loop

            def start(self):
                self.loop.call_soon(self._tick)

            def _tick(self):
                self._drain()
    """})
    assert ids == ["KTPU016"]


def test_callgraph_pool_submission_cuts_edge():
    # handing the callable to a worker pool is THE sanctioned pattern:
    # the blocking body runs on a pool slot, never the dispatcher
    good = _cg_ids({"m.py": """
        import time

        class S:
            def __init__(self, loop, pool):
                self.loop = loop
                self.pool = pool

            def start(self):
                self.loop.call_soon(self._tick)

            def _tick(self):
                self.pool.submit(self._fetch)

            def _fetch(self):
                time.sleep(0.5)
    """})
    assert good == []
    # control: the direct call IS flagged, so the silence above is the
    # edge cut, not a resolution miss
    bad = _cg_ids({"m.py": """
        import time

        class S:
            def __init__(self, loop):
                self.loop = loop

            def start(self):
                self.loop.call_soon(self._tick)

            def _tick(self):
                self._fetch()

            def _fetch(self):
                time.sleep(0.5)
    """})
    assert bad == ["KTPU016"]


def test_callgraph_recursion_bounded():
    # a call cycle must terminate the traversal, and a blocking primitive
    # inside the cycle is still found exactly once
    findings = _cg({"m.py": """
        import time

        class S:
            def __init__(self, loop):
                self.loop = loop

            def start(self):
                self.loop.call_soon(self._a)

            def _a(self):
                self._b()

            def _b(self):
                self._a()
                time.sleep(0.1)
    """})
    assert [f.pass_id for f in findings] == ["KTPU016"]
    # pure cycle, nothing blocking: quiet, and (implicitly) no hang
    assert _cg_ids({"m.py": """
        class S:
            def __init__(self, loop):
                self.loop = loop

            def start(self):
                self.loop.call_soon(self._a)

            def _a(self):
                self._b()

            def _b(self):
                self._a()
    """}) == []


def test_ktpu016_fires_three_frames_deep():
    findings = _cg({"m.py": """
        import time

        class W:
            def __init__(self, loop):
                self.loop = loop

            def start(self):
                self.loop.call_later(1.0, self._beat)

            def _beat(self):
                self._refresh()

            def _refresh(self):
                self._load()

            def _load(self):
                time.sleep(2.0)
    """})
    assert [f.pass_id for f in findings] == ["KTPU016"]
    # the chain in the message names the frames, root to primitive
    msg = findings[0].message
    assert "_beat" in msg and "_load" in msg


def test_ktpu016_quiet_on_nonblocking_callback():
    assert _cg_ids({"m.py": """
        class W:
            def __init__(self, loop):
                self.loop = loop
                self.n = 0

            def start(self):
                self.loop.call_soon(self._tick)

            def _tick(self):
                self.n += 1
                self._fold()

            def _fold(self):
                self.n *= 2
    """}) == []


def test_ktpu016_contract_root_cursor_method():
    # next_batch_nowait is dispatcher-run BY CONTRACT (the watch-cursor
    # protocol): its implementation is a root even with no visible
    # registration site in the graph
    assert _cg_ids({"m.py": """
        import time

        class Cursor:
            def next_batch_nowait(self):
                time.sleep(0.05)
    """}) == ["KTPU016"]


def test_ktpu017_fires_on_lock_across_indirect_blocking():
    findings = _cg({"m.py": """
        import time
        from kubernetes1_tpu.utils.locksan import make_lock

        class C:
            def __init__(self):
                self._mu = make_lock("C._mu")

            def put(self):
                with self._mu:
                    self._persist()

            def _persist(self):
                self._flush()

            def _flush(self):
                time.sleep(0.1)
    """})
    ids = [f.pass_id for f in findings]
    assert "KTPU017" in ids
    f17 = next(f for f in findings if f.pass_id == "KTPU017")
    assert "C._mu" in f17.message and "_flush" in f17.message


def test_ktpu017_quiet_when_critical_section_pure():
    assert "KTPU017" not in _cg_ids({"m.py": """
        import time
        from kubernetes1_tpu.utils.locksan import make_lock

        class C:
            def __init__(self):
                self._mu = make_lock("C._mu")
                self.items = {}

            def put(self, k, v):
                with self._mu:
                    self._store(k, v)
                time.sleep(0.1)  # blocking OUTSIDE the lock: legal

            def _store(self, k, v):
                self.items[k] = v
    """})


def test_callgraph_pragma_suppresses_with_justification():
    src = textwrap.dedent("""
        import time

        class S:
            def __init__(self, loop):
                self.loop = loop

            def start(self):
                self.loop.call_soon(self._tick)

            def _tick(self):
                time.sleep(0)  # ktpulint: ignore[KTPU016] zero-sleep is a scheduler hint, not a stall
    """)
    # sleep(0) is already recognized as non-blocking; use a real sleep to
    # exercise the pragma path
    src = src.replace("time.sleep(0)", "time.sleep(1)")
    assert _callgraph.analyze_sources({"m.py": src}) == []


def test_unused_pragma_detection(tmp_path):
    # a pragma whose finding no longer fires is a booby trap: it will
    # silently swallow the NEXT real finding on that line
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent("""\
        import time


        def deadline():
            t = time.time()  # ktpulint: ignore[KTPU005] audit stamp is wall clock by contract
            return t


        def pure(x):
            return x + 1  # ktpulint: ignore[KTPU005] stale: the wall-clock read moved out long ago
    """))
    from tools.ktpulint.engine import find_unused_pragmas

    findings = find_unused_pragmas([str(f)])
    assert len(findings) == 1
    assert findings[0].pass_id == "UNUSED"
    assert findings[0].line == 10
    assert "KTPU005" in findings[0].message


def test_callgraph_summary_cache_roundtrip(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("def a():\n    return 1\n")
    s1 = _callgraph.build_summaries([str(f)], str(tmp_path))
    assert (tmp_path / ".ktpulint_cache").exists()
    # warm hit: identical summaries straight from the content-hash cache
    s2 = _callgraph.build_summaries([str(f)], str(tmp_path))
    assert s2 == s1
    # content change invalidates the entry
    f.write_text("import time\n\ndef a():\n    time.sleep(1)\n")
    s3 = _callgraph.build_summaries([str(f)], str(tmp_path))
    assert s3[str(f)] != s1[str(f)]
    assert "a" in s3[str(f)]["funcs"]
    # --no-cache escape hatch agrees with the cached build
    s4 = _callgraph.build_summaries([str(f)], str(tmp_path),
                                    use_cache=False)
    assert s4[str(f)] == s3[str(f)]
