"""Conformance battery (ref: test/conformance — the reference pins a
minimal set of API behaviors every conforming cluster must exhibit).

One fixture boots the full in-process control plane; each test asserts a
behavioral contract a client may rely on.  These dedup with deeper suites
on purpose: conformance is about the CONTRACT surface, stated in one
place, cheap enough to run against any deployment of the framework.
"""

import json
import threading
import time
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import ApiError, Conflict, NotFound


@pytest.fixture(scope="module")
def cluster():
    master = Master().start()
    cs = Clientset(master.url)
    yield master, cs
    cs.close()
    master.stop()


def mk_pod(name, ns="default"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    pod.spec.containers = [t.Container(name="c", image="img",
                                       command=["sleep", "1"])]
    return pod


class TestAPIContract:
    def test_api_discovery_groups_present(self, cluster):
        master, _ = cluster
        with urllib.request.urlopen(master.url + "/healthz") as r:
            assert r.status == 200
        # every registered resource is reachable under a group prefix
        for path in ("/api/v1/pods", "/apis/apps/v1/deployments",
                     "/apis/batch/v1/jobs"):
            with urllib.request.urlopen(master.url + path) as r:
                doc = json.loads(r.read())
                assert doc["kind"].endswith("List")

    def test_create_returns_uid_and_rv(self, cluster):
        _, cs = cluster
        created = cs.pods.create(mk_pod("conf-uid"))
        assert created.metadata.uid
        assert created.metadata.resource_version
        assert created.metadata.creation_timestamp

    def test_names_are_unique_within_namespace(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-dup"))
        with pytest.raises(ApiError):
            cs.pods.create(mk_pod("conf-dup"))

    def test_get_unknown_is_404(self, cluster):
        _, cs = cluster
        with pytest.raises(NotFound):
            cs.pods.get("never-existed")

    def test_optimistic_concurrency_conflict(self, cluster):
        _, cs = cluster
        cm = t.ConfigMap()
        cm.metadata.name = "conf-occ"
        cs.configmaps.create(cm)
        a = cs.configmaps.get("conf-occ")
        b = cs.configmaps.get("conf-occ")
        a.data = {"v": "1"}
        cs.configmaps.update(a)
        b.data = {"v": "2"}
        with pytest.raises(Conflict):
            cs.configmaps.update(b)  # stale resourceVersion must 409

    def test_label_selector_list(self, cluster):
        _, cs = cluster
        p = mk_pod("conf-labeled")
        p.metadata.labels = {"conformance": "yes"}
        cs.pods.create(p)
        items, _ = cs.pods.list(namespace="default",
                                label_selector="conformance=yes")
        assert [i.metadata.name for i in items] == ["conf-labeled"]

    def test_namespace_isolation(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-ns-a", ns="conf-ns-one"))
        items, _ = cs.pods.list(namespace="conf-ns-two")
        assert all(i.metadata.name != "conf-ns-a" for i in items)


class TestWatchContract:
    def test_watch_resumes_from_resource_version(self, cluster):
        _, cs = cluster
        _, rv = cs.pods.list(namespace="default")
        cs.pods.create(mk_pod("conf-watch-1"))
        seen = []
        with cs.pods.watch(namespace="default", resource_version=rv) as stream:
            for etype, obj in stream:
                seen.append((etype, obj["metadata"]["name"]))
                break
        assert ("ADDED", "conf-watch-1") in seen

    def test_watch_sees_delete(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-watch-del"))
        _, rv = cs.pods.list(namespace="default")
        got = []

        def watcher():
            with cs.pods.watch(namespace="default",
                               resource_version=rv) as stream:
                for etype, obj in stream:
                    if obj["metadata"]["name"] == "conf-watch-del":
                        got.append(etype)
                        return

        th = threading.Thread(target=watcher, daemon=True)
        th.start()
        time.sleep(0.2)
        cs.pods.delete("conf-watch-del", grace_seconds=0)
        th.join(timeout=10)
        assert got and got[0] == "DELETED"

    def test_compacted_watch_410s(self, cluster):
        """A watch from an ancient resourceVersion must signal Expired so
        clients relist (the reflector contract)."""
        master, cs = cluster
        store = master.store
        # force compaction if supported; at minimum rv=1 must not hang
        if hasattr(store, "compact"):
            items, rv = cs.pods.list(namespace="default")
            store.compact(int(rv) - 1 if int(rv) > 1 else 1)
        from kubernetes1_tpu.machinery.errors import TooOldResourceVersion

        try:
            with cs.pods.watch(namespace="default",
                               resource_version="1") as stream:
                for _ in stream:
                    break
        except TooOldResourceVersion:
            pass  # 410 is the conforming answer post-compaction


class TestSubresourceContract:
    def test_status_update_does_not_touch_spec(self, cluster):
        _, cs = cluster
        pod = cs.pods.create(mk_pod("conf-status"))
        pod.status.phase = t.POD_RUNNING
        pod.spec.containers[0].image = "mutated"  # must be ignored
        cs.pods.update_status(pod)
        got = cs.pods.get("conf-status")
        assert got.status.phase == t.POD_RUNNING
        assert got.spec.containers[0].image == "img"

    def test_binding_sets_node_and_rebind_conflicts(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-bind"))
        binding = t.Binding(target_node="conf-node")
        binding.metadata.name = "conf-bind"
        cs.bind("default", "conf-bind", binding)
        assert cs.pods.get("conf-bind").spec.node_name == "conf-node"
        # same-node re-bind is idempotent (scheduler retry tolerance);
        # binding to a DIFFERENT node must 409
        cs.bind("default", "conf-bind", binding)
        other = t.Binding(target_node="other-node")
        other.metadata.name = "conf-bind"
        with pytest.raises(Conflict):
            cs.bind("default", "conf-bind", other)

    def test_tpu_limit_rewritten_to_v2(self, cluster):
        """The fork's signature behavior: google.com/tpu container limits
        become pod-level ExtendedResources."""
        _, cs = cluster
        pod = mk_pod("conf-tpu")
        pod.spec.containers[0].resources.limits = {"google.com/tpu": 2}
        created = cs.pods.create(pod)
        assert len(created.spec.extended_resources) == 1
        er = created.spec.extended_resources[0]
        assert er.resource == "google.com/tpu" and er.quantity == 2
        assert created.spec.containers[0].extended_resource_requests == [er.name]


class TestAuthContract:
    def test_rbac_denies_until_granted(self):
        master = Master(authorization_mode="Node,RBAC", token="root",
                        static_tokens={"usr": ("u1", [])}).start()
        admin = Clientset(master.url, token="root")
        user = Clientset(master.url, token="usr")
        try:
            with pytest.raises(ApiError):
                user.pods.list(namespace="default")
            role = t.ClusterRole()
            role.metadata.name = "conf-reader"
            role.rules = [t.PolicyRule(verbs=["list"], resources=["pods"])]
            admin.clusterroles.create(role, "")
            rb = t.ClusterRoleBinding()
            rb.metadata.name = "conf-reader-b"
            rb.subjects = [t.Subject(kind="User", name="u1")]
            rb.role_ref = t.RoleRef(kind="ClusterRole", name="conf-reader")
            admin.clusterrolebindings.create(rb, "")
            items, _ = user.pods.list(namespace="default")
            assert items == []
        finally:
            user.close()
            admin.close()
            master.stop()


class TestObjectMetaContract:
    def test_generate_name_yields_unique_names(self, cluster):
        _, cs = cluster
        names = set()
        for _ in range(5):
            p = mk_pod("")
            p.metadata.generate_name = "gen-"
            created = cs.pods.create(p)
            assert created.metadata.name.startswith("gen-")
            names.add(created.metadata.name)
        assert len(names) == 5

    def test_resource_version_monotonic_across_kinds(self, cluster):
        _, cs = cluster
        a = cs.configmaps.create(_cm("rv-a"))
        b = cs.secrets.create(_sec("rv-b"))
        assert int(b.metadata.resource_version) > \
            int(a.metadata.resource_version)

    def test_labels_annotations_roundtrip(self, cluster):
        _, cs = cluster
        cm = _cm("meta-rt")
        cm.metadata.labels = {"a/b": "c", "x": ""}
        cm.metadata.annotations = {"long": "v" * 4096}
        got = cs.configmaps.create(cm)
        assert got.metadata.labels == {"a/b": "c", "x": ""}
        assert got.metadata.annotations["long"] == "v" * 4096

    def test_error_shape_is_status_object(self, cluster):
        master, _ = cluster
        import urllib.error

        try:
            urllib.request.urlopen(
                master.url + "/api/v1/namespaces/default/pods/nope-404")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            assert e.code == 404
            assert body.get("kind") == "Status"
            assert body.get("code") == 404
            assert body.get("reason") == "NotFound"


class TestFieldSelectorContract:
    def test_field_selector_phase_and_nodename(self, cluster):
        _, cs = cluster
        p = cs.pods.create(mk_pod("fsel-1"))
        pods, _ = cs.pods.list(namespace="default",
                               field_selector="status.phase=Pending")
        assert any(x.metadata.name == "fsel-1" for x in pods)
        pods, _ = cs.pods.list(namespace="default",
                               field_selector="spec.nodeName=nowhere")
        assert not any(x.metadata.name == "fsel-1" for x in pods)


class TestPatchContract:
    def test_merge_patch_sets_and_null_deletes(self, cluster):
        _, cs = cluster
        cs.configmaps.create(_cm("patchy", data={"keep": "1", "drop": "2"}))
        cs.configmaps.patch("patchy",
                            {"data": {"drop": None, "new": "3"}}, "default")
        got = cs.configmaps.get("patchy", "default")
        assert got.data == {"keep": "1", "new": "3"}

    def test_patch_cannot_change_immutable_node_name(self, cluster):
        _, cs = cluster
        from kubernetes1_tpu.machinery import Forbidden

        p = cs.pods.create(mk_pod("immut-1"))
        binding = t.Binding(target_node="n-1")
        binding.metadata.name = "immut-1"
        cs.bind("default", "immut-1", binding)
        with pytest.raises(Forbidden):
            cs.pods.patch("immut-1", {"spec": {"nodeName": "n-2"}},
                          "default")


class TestServiceContract:
    def test_cluster_ip_allocated_and_stable(self, cluster):
        _, cs = cluster
        svc = t.Service()
        svc.metadata.name = "conf-svc"
        svc.spec.selector = {"app": "x"}
        svc.spec.ports = [t.ServicePort(port=80)]
        created = cs.services.create(svc, "default")
        assert created.spec.cluster_ip.startswith("10.96.")
        # updates must not re-allocate the IP
        created.metadata.labels = {"touched": "yes"}
        updated = cs.services.update(created)
        assert updated.spec.cluster_ip == created.spec.cluster_ip

    def test_headless_service_keeps_none(self, cluster):
        _, cs = cluster
        svc = t.Service()
        svc.metadata.name = "conf-headless"
        svc.spec.cluster_ip = "None"
        svc.spec.selector = {"app": "y"}
        svc.spec.ports = [t.ServicePort(port=80)]
        created = cs.services.create(svc, "default")
        assert created.spec.cluster_ip == "None"

    def test_nodeport_allocated_in_range(self, cluster):
        _, cs = cluster
        svc = t.Service()
        svc.metadata.name = "conf-np"
        svc.spec.type = "NodePort"
        svc.spec.selector = {"app": "z"}
        svc.spec.ports = [t.ServicePort(port=80)]
        created = cs.services.create(svc, "default")
        assert 30000 <= created.spec.ports[0].node_port <= 32767


class TestCRDContract:
    def test_crd_registration_and_custom_resource_crud(self, cluster):
        _, cs = cluster
        crd = t.CustomResourceDefinition()
        crd.metadata.name = "trainjobs.ml.ktpu.io"
        crd.spec.group = "ml.ktpu.io"
        crd.spec.version = "v1"
        crd.spec.names = t.CRDNames(kind="TrainJob", plural="trainjobs")
        crd.spec.scope = "Namespaced"
        cs.resource("customresourcedefinitions").create(crd, "")
        tj = {"apiVersion": "ml.ktpu.io/v1", "kind": "TrainJob",
              "metadata": {"name": "t1", "namespace": "default"},
              "spec": {"chips": 8}}
        created = cs.api.request(
            "POST", "/apis/ml.ktpu.io/v1/namespaces/default/trainjobs",
            body=tj)
        assert created["metadata"]["uid"]
        got = cs.api.request(
            "GET", "/apis/ml.ktpu.io/v1/namespaces/default/trainjobs/t1")
        assert got["spec"]["chips"] == 8
        cs.api.request(
            "DELETE", "/apis/ml.ktpu.io/v1/namespaces/default/trainjobs/t1")


def _cm(name, data=None):
    cm = t.ConfigMap(data=data or {"k": "v"})
    cm.metadata.name = name
    return cm


def _sec(name):
    s = t.Secret(data={"k": "v"})
    s.metadata.name = name
    return s


class TestControllerConformance:
    """Contracts that need the controller manager (namespace lifecycle,
    ServiceAccount defaulting, ownerRef cascade — ref conformance's
    'Guaranteed' controller behaviors)."""

    @pytest.fixture(scope="class")
    def kcm_cluster(self):
        from kubernetes1_tpu.controllers import ControllerManager

        master = Master().start()
        cs = Clientset(master.url)
        cm = ControllerManager(cs)
        cm.start()
        yield master, cs
        cm.stop()
        cs.close()
        master.stop()

    def test_new_namespace_gets_default_serviceaccount(self, kcm_cluster):
        _, cs = kcm_cluster
        ns = t.Namespace()
        ns.metadata.name = "conf-ns-sa"
        cs.namespaces.create(ns, "")
        from kubernetes1_tpu.utils.waitutil import must_poll_until

        must_poll_until(
            lambda: any(sa.metadata.name == "default"
                        for sa in cs.serviceaccounts.list(
                            namespace="conf-ns-sa")[0]),
            timeout=15.0, desc="default SA created")

    def test_namespace_delete_cascades_objects(self, kcm_cluster):
        _, cs = kcm_cluster
        from kubernetes1_tpu.utils.waitutil import must_poll_until

        ns = t.Namespace()
        ns.metadata.name = "conf-ns-gone"
        cs.namespaces.create(ns, "")
        cm = _cm("inside")
        cm.metadata.namespace = "conf-ns-gone"
        cs.configmaps.create(cm, "conf-ns-gone")
        cs.namespaces.delete("conf-ns-gone", "")
        must_poll_until(
            lambda: not _exists(cs, "configmaps", "inside", "conf-ns-gone"),
            timeout=20.0, desc="namespaced object cascaded")
        must_poll_until(
            lambda: not _exists(cs, "namespaces", "conf-ns-gone", ""),
            timeout=20.0, desc="namespace finalized")

    def test_owner_reference_cascade(self, kcm_cluster):
        _, cs = kcm_cluster
        from kubernetes1_tpu.utils.waitutil import must_poll_until

        owner = cs.configmaps.create(_cm("gc-owner"))
        child = _cm("gc-child")
        child.metadata.owner_references = [t.OwnerReference(
            api_version="v1", kind="ConfigMap",
            name="gc-owner", uid=owner.metadata.uid)]
        cs.configmaps.create(child)
        cs.configmaps.delete("gc-owner", "default")
        must_poll_until(
            lambda: not _exists(cs, "configmaps", "gc-child", "default"),
            timeout=20.0, desc="orphaned child garbage-collected")

    def test_deployment_materializes_replicaset_and_pods(self, kcm_cluster):
        _, cs = kcm_cluster
        from kubernetes1_tpu.utils.waitutil import must_poll_until

        dep = t.Deployment()
        dep.metadata.name = "conf-dep"
        dep.spec.replicas = 2
        dep.spec.selector = t.LabelSelector(match_labels={"app": "cd"})
        tmpl = t.PodTemplateSpec()
        tmpl.metadata.labels = {"app": "cd"}
        tmpl.spec.containers = [t.Container(name="c", image="i",
                                            command=["sleep", "9"])]
        dep.spec.template = tmpl
        cs.deployments.create(dep, "default")
        must_poll_until(
            lambda: len(cs.pods.list(namespace="default",
                                     label_selector="app=cd")[0]) == 2,
            timeout=20.0, desc="deployment -> RS -> 2 pods")
        rss, _ = cs.replicasets.list(namespace="default",
                                     label_selector="app=cd")
        assert len(rss) == 1
        assert any(o.kind == "Deployment"
                   for o in rss[0].metadata.owner_references)


def _exists(cs, resource, name, ns):
    try:
        cs.resource(resource).get(name, ns)
        return True
    except NotFound:
        return False
