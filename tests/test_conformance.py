"""Conformance battery (ref: test/conformance — the reference pins a
minimal set of API behaviors every conforming cluster must exhibit).

One fixture boots the full in-process control plane; each test asserts a
behavioral contract a client may rely on.  These dedup with deeper suites
on purpose: conformance is about the CONTRACT surface, stated in one
place, cheap enough to run against any deployment of the framework.
"""

import json
import threading
import time
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import ApiError, Conflict, NotFound


@pytest.fixture(scope="module")
def cluster():
    master = Master().start()
    cs = Clientset(master.url)
    yield master, cs
    cs.close()
    master.stop()


def mk_pod(name, ns="default"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    pod.spec.containers = [t.Container(name="c", image="img",
                                       command=["sleep", "1"])]
    return pod


class TestAPIContract:
    def test_api_discovery_groups_present(self, cluster):
        master, _ = cluster
        with urllib.request.urlopen(master.url + "/healthz") as r:
            assert r.status == 200
        # every registered resource is reachable under a group prefix
        for path in ("/api/v1/pods", "/apis/apps/v1/deployments",
                     "/apis/batch/v1/jobs"):
            with urllib.request.urlopen(master.url + path) as r:
                doc = json.loads(r.read())
                assert doc["kind"].endswith("List")

    def test_create_returns_uid_and_rv(self, cluster):
        _, cs = cluster
        created = cs.pods.create(mk_pod("conf-uid"))
        assert created.metadata.uid
        assert created.metadata.resource_version
        assert created.metadata.creation_timestamp

    def test_names_are_unique_within_namespace(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-dup"))
        with pytest.raises(ApiError):
            cs.pods.create(mk_pod("conf-dup"))

    def test_get_unknown_is_404(self, cluster):
        _, cs = cluster
        with pytest.raises(NotFound):
            cs.pods.get("never-existed")

    def test_optimistic_concurrency_conflict(self, cluster):
        _, cs = cluster
        cm = t.ConfigMap()
        cm.metadata.name = "conf-occ"
        cs.configmaps.create(cm)
        a = cs.configmaps.get("conf-occ")
        b = cs.configmaps.get("conf-occ")
        a.data = {"v": "1"}
        cs.configmaps.update(a)
        b.data = {"v": "2"}
        with pytest.raises(Conflict):
            cs.configmaps.update(b)  # stale resourceVersion must 409

    def test_label_selector_list(self, cluster):
        _, cs = cluster
        p = mk_pod("conf-labeled")
        p.metadata.labels = {"conformance": "yes"}
        cs.pods.create(p)
        items, _ = cs.pods.list(namespace="default",
                                label_selector="conformance=yes")
        assert [i.metadata.name for i in items] == ["conf-labeled"]

    def test_namespace_isolation(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-ns-a", ns="conf-ns-one"))
        items, _ = cs.pods.list(namespace="conf-ns-two")
        assert all(i.metadata.name != "conf-ns-a" for i in items)


class TestWatchContract:
    def test_watch_resumes_from_resource_version(self, cluster):
        _, cs = cluster
        _, rv = cs.pods.list(namespace="default")
        cs.pods.create(mk_pod("conf-watch-1"))
        seen = []
        with cs.pods.watch(namespace="default", resource_version=rv) as stream:
            for etype, obj in stream:
                seen.append((etype, obj["metadata"]["name"]))
                break
        assert ("ADDED", "conf-watch-1") in seen

    def test_watch_sees_delete(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-watch-del"))
        _, rv = cs.pods.list(namespace="default")
        got = []

        def watcher():
            with cs.pods.watch(namespace="default",
                               resource_version=rv) as stream:
                for etype, obj in stream:
                    if obj["metadata"]["name"] == "conf-watch-del":
                        got.append(etype)
                        return

        th = threading.Thread(target=watcher, daemon=True)
        th.start()
        time.sleep(0.2)
        cs.pods.delete("conf-watch-del", grace_seconds=0)
        th.join(timeout=10)
        assert got and got[0] == "DELETED"

    def test_compacted_watch_410s(self, cluster):
        """A watch from an ancient resourceVersion must signal Expired so
        clients relist (the reflector contract)."""
        master, cs = cluster
        store = master.store
        # force compaction if supported; at minimum rv=1 must not hang
        if hasattr(store, "compact"):
            items, rv = cs.pods.list(namespace="default")
            store.compact(int(rv) - 1 if int(rv) > 1 else 1)
        from kubernetes1_tpu.machinery.errors import TooOldResourceVersion

        try:
            with cs.pods.watch(namespace="default",
                               resource_version="1") as stream:
                for _ in stream:
                    break
        except TooOldResourceVersion:
            pass  # 410 is the conforming answer post-compaction


class TestSubresourceContract:
    def test_status_update_does_not_touch_spec(self, cluster):
        _, cs = cluster
        pod = cs.pods.create(mk_pod("conf-status"))
        pod.status.phase = t.POD_RUNNING
        pod.spec.containers[0].image = "mutated"  # must be ignored
        cs.pods.update_status(pod)
        got = cs.pods.get("conf-status")
        assert got.status.phase == t.POD_RUNNING
        assert got.spec.containers[0].image == "img"

    def test_binding_sets_node_and_rebind_conflicts(self, cluster):
        _, cs = cluster
        cs.pods.create(mk_pod("conf-bind"))
        binding = t.Binding(target_node="conf-node")
        binding.metadata.name = "conf-bind"
        cs.bind("default", "conf-bind", binding)
        assert cs.pods.get("conf-bind").spec.node_name == "conf-node"
        # same-node re-bind is idempotent (scheduler retry tolerance);
        # binding to a DIFFERENT node must 409
        cs.bind("default", "conf-bind", binding)
        other = t.Binding(target_node="other-node")
        other.metadata.name = "conf-bind"
        with pytest.raises(Conflict):
            cs.bind("default", "conf-bind", other)

    def test_tpu_limit_rewritten_to_v2(self, cluster):
        """The fork's signature behavior: google.com/tpu container limits
        become pod-level ExtendedResources."""
        _, cs = cluster
        pod = mk_pod("conf-tpu")
        pod.spec.containers[0].resources.limits = {"google.com/tpu": 2}
        created = cs.pods.create(pod)
        assert len(created.spec.extended_resources) == 1
        er = created.spec.extended_resources[0]
        assert er.resource == "google.com/tpu" and er.quantity == 2
        assert created.spec.containers[0].extended_resource_requests == [er.name]


class TestAuthContract:
    def test_rbac_denies_until_granted(self):
        master = Master(authorization_mode="Node,RBAC", token="root",
                        static_tokens={"usr": ("u1", [])}).start()
        admin = Clientset(master.url, token="root")
        user = Clientset(master.url, token="usr")
        try:
            with pytest.raises(ApiError):
                user.pods.list(namespace="default")
            role = t.ClusterRole()
            role.metadata.name = "conf-reader"
            role.rules = [t.PolicyRule(verbs=["list"], resources=["pods"])]
            admin.clusterroles.create(role, "")
            rb = t.ClusterRoleBinding()
            rb.metadata.name = "conf-reader-b"
            rb.subjects = [t.Subject(kind="User", name="u1")]
            rb.role_ref = t.RoleRef(kind="ClusterRole", name="conf-reader")
            admin.clusterrolebindings.create(rb, "")
            items, _ = user.pods.list(namespace="default")
            assert items == []
        finally:
            user.close()
            admin.close()
            master.stop()
