"""Extensibility tests: CRDs served as dynamic resources (apiextensions-
apiserver analog) and APIService aggregation proxying (kube-aggregator
analog)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import ApiError, Invalid, NotFound
from kubernetes1_tpu.machinery.scheme import Unstructured


@pytest.fixture()
def master():
    m = Master().start()
    yield m
    m.stop()


def make_crd(kind="TPUJobProfile", plural="tpujobprofiles", group="example.ktpu.io",
             scope="Namespaced"):
    crd = t.CustomResourceDefinition()
    crd.metadata.name = f"{plural}.{group}"
    crd.spec.group = group
    crd.spec.version = "v1"
    crd.spec.names = t.CRDNames(plural=plural, singular=kind.lower(), kind=kind)
    crd.spec.scope = scope
    return crd


class TestCRDs:
    def test_crd_lifecycle_create_use_delete(self, master):
        cs = Clientset(master.url)
        cs.customresourcedefinitions.create(make_crd())

        obj = Unstructured(kind="TPUJobProfile", api_version="example.ktpu.io/v1")
        obj.metadata.name = "bert-profile"
        obj.metadata.namespace = "default"
        obj.content["spec"] = {"topology": "4x4x8", "chips": 128}
        created = cs.resource("tpujobprofiles").create(obj)
        assert created.content["spec"]["chips"] == 128
        assert created.metadata.uid

        got = cs.resource("tpujobprofiles").get("bert-profile")
        assert got.content["spec"]["topology"] == "4x4x8"

        items, _ = cs.resource("tpujobprofiles").list(namespace="default")
        assert [o.metadata.name for o in items] == ["bert-profile"]

        # update round-trips free-form content
        got.content["spec"]["chips"] = 256
        updated = cs.resource("tpujobprofiles").update(got)
        assert updated.content["spec"]["chips"] == 256

        cs.resource("tpujobprofiles").delete("bert-profile")
        with pytest.raises(NotFound):
            cs.resource("tpujobprofiles").get("bert-profile")

        # deleting the CRD unregisters the resource
        cs.customresourcedefinitions.delete("tpujobprofiles.example.ktpu.io", "")
        with pytest.raises(ApiError):
            cs.resource("tpujobprofiles").list(namespace="default")
        cs.close()

    def test_crd_watch_stream(self, master):
        cs = Clientset(master.url)
        cs.customresourcedefinitions.create(make_crd(kind="Widget", plural="widgets"))
        _, rv = cs.resource("widgets").list(namespace="default")
        w = cs.resource("widgets").watch(namespace="default", resource_version=rv,
                                         timeout_seconds=5)
        obj = Unstructured(kind="Widget", api_version="example.ktpu.io/v1")
        obj.metadata.name = "w1"
        obj.metadata.namespace = "default"
        cs.resource("widgets").create(obj)
        etype, obj_dict = next(iter(w))
        assert etype == "ADDED" and obj_dict["metadata"]["name"] == "w1"
        w.close()
        cs.close()

    def test_crd_cannot_shadow_builtin(self, master):
        cs = Clientset(master.url)
        with pytest.raises(Invalid, match="shadows"):
            cs.customresourcedefinitions.create(
                make_crd(kind="FakePod", plural="pods")
            )
        # kind collision hijacks decoding of the built-in — also rejected
        with pytest.raises(Invalid, match="shadows"):
            cs.customresourcedefinitions.create(
                make_crd(kind="Pod", plural="foopods")
            )
        cs.close()

    def test_mismatched_kind_body_rejected(self, master):
        """A typo'd kind must 400 at create, not silently persist as
        Unstructured into a typed registry."""
        from kubernetes1_tpu.machinery import BadRequest

        cs = Clientset(master.url)
        with pytest.raises(BadRequest, match="does not match resource"):
            cs.api.request(
                "POST", "/api/v1/namespaces/default/configmaps",
                body={"kind": "Configmap", "apiVersion": "v1",
                      "metadata": {"name": "oops"}, "data": {}},
            )
        cs.close()

    def test_crd_update_reregisters_names(self, master):
        cs = Clientset(master.url)
        cs.customresourcedefinitions.create(make_crd(kind="Thing", plural="things"))
        crd = cs.customresourcedefinitions.get("things.example.ktpu.io", "")
        crd.spec.names = t.CRDNames(plural="stuffs", singular="stuff", kind="Stuff")
        cs.customresourcedefinitions.update(crd)
        # old plural gone, new plural served
        with pytest.raises(ApiError):
            cs.resource("things").list(namespace="default")
        items, _ = cs.resource("stuffs").list(namespace="default")
        assert items == []
        cs.close()

    def test_crd_survives_wal_restart(self, tmp_path):
        wal = str(tmp_path / "wal.jsonl")
        m1 = Master(wal_path=wal).start()
        cs1 = Clientset(m1.url)
        cs1.customresourcedefinitions.create(make_crd(kind="Gadget", plural="gadgets"))
        obj = Unstructured(kind="Gadget", api_version="example.ktpu.io/v1")
        obj.metadata.name = "g1"
        obj.metadata.namespace = "default"
        obj.content["spec"] = {"size": 3}
        cs1.resource("gadgets").create(obj)
        cs1.close()
        m1.stop()

        m2 = Master(wal_path=wal).start()
        cs2 = Clientset(m2.url)
        got = cs2.resource("gadgets").get("g1")
        assert got.metadata.name == "g1"
        assert got.content["spec"] == {"size": 3}
        cs2.close()
        m2.stop()


class _EchoAPIHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        payload = json.dumps(
            {"kind": "EchoList", "path": self.path, "served_by": "aggregated"}
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class TestAPIServiceShadowGuard:
    def test_apiservice_cannot_claim_builtin_group(self, master):
        """ADVICE r1: an APIService claiming a built-in group/version would
        hijack built-in routing (aggregation is consulted before built-in
        dispatch). The registry rejects the shadow."""
        from kubernetes1_tpu.machinery import Invalid

        cs = Clientset(master.url)
        try:
            for group, version in (("apps", "v1"), ("rbac", "v1"), ("batch", "v1")):
                apisvc = t.APIService()
                apisvc.metadata.name = f"{version}.{group}"
                apisvc.spec.group = group
                apisvc.spec.version = version
                apisvc.spec.service_namespace = "kube-system"
                apisvc.spec.service_name = "rogue"
                with pytest.raises(Invalid, match="shadows"):
                    cs.apiservices.create(apisvc)
        finally:
            cs.close()


class TestAggregation:
    def test_apiservice_proxies_to_backing_endpoints(self, master):
        cs = Clientset(master.url)
        backend = ThreadingHTTPServer(("127.0.0.1", 0), _EchoAPIHandler)
        th = threading.Thread(target=backend.serve_forever, daemon=True)
        th.start()
        port = backend.server_address[1]
        try:
            svc = t.Service()
            svc.metadata.name = "echo-api"
            svc.metadata.namespace = "kube-system"
            svc.spec.ports = [t.ServicePort(port=443)]
            cs.services.create(svc, "kube-system")
            eps = t.Endpoints(
                subsets=[
                    t.EndpointSubset(
                        addresses=[t.EndpointAddress(ip="127.0.0.1")],
                        ports=[t.EndpointPort(port=port)],
                    )
                ]
            )
            eps.metadata.name = "echo-api"
            eps.metadata.namespace = "kube-system"
            cs.endpoints.create(eps, "kube-system")

            apisvc = t.APIService()
            apisvc.metadata.name = "v1.echo.ktpu.io"
            apisvc.spec.group = "echo.ktpu.io"
            apisvc.spec.version = "v1"
            apisvc.spec.service_namespace = "kube-system"
            apisvc.spec.service_name = "echo-api"
            cs.apiservices.create(apisvc)

            data = cs.api.request("GET", "/apis/echo.ktpu.io/v1/echoes")
            assert data["served_by"] == "aggregated"
            assert data["path"] == "/apis/echo.ktpu.io/v1/echoes"
        finally:
            backend.shutdown()
            backend.server_close()
            cs.close()
