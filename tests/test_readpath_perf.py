"""Read-path smoke guards (tier-1, non-slow).

Three properties the watch-cache + once-per-revision serialization layer
must keep as the tree grows:

1. under a multi-watcher churn loop the serialization-cache hit ratio
   stays > 0.9 (N watchers + lists fan out the SAME bytes);
2. serialization work per event is O(1) in watcher count — K ∈ {1, 8, 32}
   concurrent watchers cost ~the same number of encodes as one;
3. the read-path modules stay at zero ktpulint findings.
"""

import os
import threading
import time

from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, SharedInformer

from tests.test_machinery import make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the modules this PR's read path lives in
READPATH_MODULES = [
    "kubernetes1_tpu/storage/cacher.py",
    "kubernetes1_tpu/storage/store.py",
    "kubernetes1_tpu/machinery/scheme.py",
    "kubernetes1_tpu/apiserver/server.py",
]


def _drain(stream, sink, done_names):
    """Consume watch frames until every expected name has been seen."""
    for ev_type, obj in stream:
        name = (obj.get("metadata") or {}).get("name", "")
        sink.append((ev_type, name))
        done_names.discard(name)
        if not done_names:
            return


def _run_churn(master, cs, n_watchers, n_pods, tag):
    """n_watchers concurrent watch streams over one churn of n_pods
    creates; returns the serialization-cache (hits, misses) delta."""
    scheme = master.scheme
    streams, threads, sinks = [], [], []
    expected = {f"{tag}-{i}" for i in range(n_pods)}
    for _ in range(n_watchers):
        s = cs.pods.watch(namespace="default")
        sink = []
        th = threading.Thread(target=_drain,
                              args=(s, sink, set(expected)), daemon=True)
        th.start()
        streams.append(s)
        threads.append(th)
        sinks.append(sink)
    h0, m0 = scheme.serialization_cache.stats()
    for i in range(n_pods):
        cs.pods.create(make_pod(f"{tag}-{i}"))
    for th in threads:
        th.join(timeout=20)
    assert not any(th.is_alive() for th in threads), "watcher starved"
    for s in streams:
        s.close()
    h1, m1 = scheme.serialization_cache.stats()
    for sink in sinks:
        assert len([1 for t, n in sink if n.startswith(tag)]) >= n_pods
    return h1 - h0, m1 - m0


class TestOncePerRevisionSerialization:
    def test_one_encode_serves_k_watchers(self):
        """Encodes (cache misses) per churn must not scale with watcher
        count: K watchers each receive every event, but the frame bytes
        are built once per (object, revision)."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            n_pods = 10
            misses = {}
            for k in (1, 8, 32):
                _hits, m = _run_churn(master, cs, k, n_pods, f"fan{k}")
                misses[k] = m
            # one encode per create response (+ rare benign double-encode
            # races between the response thread and fan-out threads that
            # miss concurrently); NEVER one per watcher per event.
            # 32 watchers x 10 events = 320 deliveries; O(K) behavior
            # would put misses[32] near 320.
            assert misses[32] <= misses[1] + 2 * n_pods, misses
            assert misses[32] <= 4 * n_pods, misses
        finally:
            cs.close()
            master.stop()

    def test_hit_ratio_above_0_9_under_multiwatcher_churn(self):
        """The smoke guard: with 16 watchers fanning out each event, >90%
        of serializations must come from the cache."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            hits, misses = _run_churn(master, cs, 16, 20, "churn")
            # a few full lists ride the same cache entries
            for _ in range(3):
                items, _rv = cs.pods.list(namespace="default")
                assert len(items) >= 20
            h1, m1 = master.scheme.serialization_cache.stats()
            total = h1 + m1
            ratio = h1 / total
            assert ratio > 0.9, f"hit ratio {ratio:.3f} ({h1}/{total})"
            # and the apiserver reports it on /metrics
            import urllib.request

            raw = urllib.request.urlopen(
                master.url + "/metrics", timeout=5).read().decode()
            assert "ktpu_encode_cache_hit_ratio" in raw
            assert "ktpu_watch_slow_consumer_evictions_total" in raw
        finally:
            cs.close()
            master.stop()


class TestSlowConsumerEvictionE2E:
    def test_wedged_informer_gets_410_and_relists_without_loss(self):
        """A watcher that stops draining is evicted (bounded queue), the
        client sees 410 Expired, and the informer's relist converges to
        the true state — no event loss, no unbounded queue."""
        master = Master(watch_queue_limit=4).start()
        cs = Clientset(master.url)
        try:
            inf = SharedInformer(cs.pods, namespace="default")
            gate = threading.Event()
            inf.add_handler(on_add=lambda obj: gate.wait(timeout=30))
            inf.start()
            assert inf.wait_for_sync(10)
            # big payloads defeat TCP buffering so the server-side queue
            # (limit 4) actually fills while the handler is gated
            blob = "x" * 65536
            created = 0
            deadline = time.monotonic() + 30
            while (master.cacher.watch_evictions == 0
                   and time.monotonic() < deadline):
                pod = make_pod(f"slow-{created}")
                pod.metadata.annotations["blob"] = blob
                cs.pods.create(pod)
                created += 1
            assert master.cacher.watch_evictions >= 1, \
                f"no eviction after {created} events"
            gate.set()  # unwedge: drain, take the 410, relist
            deadline = time.monotonic() + 30
            want = {f"slow-{i}" for i in range(created)}
            while time.monotonic() < deadline:
                have = {k.split("/", 1)[1] for k in inf.keys()}
                if have == want:
                    break
                time.sleep(0.1)
            assert {k.split("/", 1)[1] for k in inf.keys()} == want, \
                "informer cache diverged after eviction"
            assert inf.relists >= 2, "eviction did not force a relist"
            inf.stop()
        finally:
            cs.close()
            master.stop()


class TestReadpathLintClean:
    def test_zero_ktpulint_findings_in_readpath_modules(self):
        from tools.ktpulint import lint_paths

        findings = lint_paths(
            [os.path.join(REPO, m) for m in READPATH_MODULES])
        rendered = "\n".join(
            os.path.relpath(f.path, REPO) + f":{f.line}: {f.pass_id} "
            f"{f.message}" for f in findings)
        assert not findings, f"ktpulint findings:\n{rendered}"
