"""Native component tests: the C++ libtpu device plugin and the C++ TPU
metrics exporter must interoperate with the Python control plane over the
same unix-socket protocol / Prometheus text format as the Python
implementations (deviceplugin/api.py is the contract)."""

import os
import subprocess
import time
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.deviceplugin.api import PluginClient, plugin_socket_path
from kubernetes1_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "kubernetes1_tpu", "native")


@pytest.fixture(scope="session")
def native_bins():
    res = subprocess.run(
        ["make", "-C", NATIVE_DIR], capture_output=True, text=True
    )
    if res.returncode != 0:
        pytest.fail(f"native build failed:\n{res.stdout}\n{res.stderr}")
    bins = {
        "plugin": os.path.join(NATIVE_DIR, "bin", "ktpu-tpu-plugin"),
        "exporter": os.path.join(NATIVE_DIR, "bin", "ktpu-metrics-exporter"),
    }
    for path in bins.values():
        assert os.access(path, os.X_OK)
    return bins


def start_native_plugin(binary, plugin_dir, fake="v5e:4:sliceN:0"):
    env = dict(os.environ, KTPU_FAKE_TPUS=fake)
    proc = subprocess.Popen(
        [binary, "--plugin-dir", str(plugin_dir)],
        env=env, stdout=subprocess.PIPE, text=True,
    )
    sock = plugin_socket_path(str(plugin_dir), "google.com/tpu")
    deadline = time.monotonic() + 5
    while not os.path.exists(sock):
        if time.monotonic() > deadline:
            proc.terminate()
            proc.wait(timeout=5)
            raise TimeoutError("native plugin socket never appeared")
        time.sleep(0.05)
    return proc


class TestNativePluginProtocol:
    def test_four_rpcs(self, native_bins, tmp_path):
        proc = start_native_plugin(native_bins["plugin"], tmp_path, "v5p:4:sZ:1")
        try:
            client = PluginClient(plugin_socket_path(str(tmp_path), "google.com/tpu"))
            info = client.call("GetPluginInfo")
            assert info["name"] == "google.com/tpu"
            assert info["device_count"] == 4
            assert info["native"] is True

            devices = next(client.list_and_watch())
            assert len(devices) == 4
            assert devices[0]["health"] == t.DEVICE_HEALTHY
            attrs = devices[0]["attributes"]
            assert attrs[t.ATTR_TPU_SLICE] == "sZ"
            assert attrs[t.ATTR_TPU_TOPOLOGY] == "2x2x1"
            assert attrs[t.ATTR_TPU_HOST_INDEX] == "1"

            ok = client.call("AdmitPod", {
                "pod_uid": "u1",
                "assignments": {"req": [devices[0]["id"], devices[1]["id"]]},
            })
            assert ok == {"allowed": True}
            bad = client.call("AdmitPod", {
                "pod_uid": "u2", "assignments": {"req": ["ghost"]},
            })
            assert bad["allowed"] is False and "ghost" in bad["reason"]

            spec = client.call("InitContainer", {
                "device_ids": [d["id"] for d in devices[:2]],
                "pod_annotations": {
                    "tpu.ktpu.io/worker-id": "5",
                    "tpu.ktpu.io/coordinator-address": "host0:8476",
                    "tpu.ktpu.io/worker-hostnames": "host0,host1",
                },
            })
            envs = spec["envs"]
            assert envs["TPU_VISIBLE_CHIPS"] == "0,1"
            assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"
            assert envs["TPU_WORKER_ID"] == "5"
            assert envs["JAX_COORDINATOR_ADDRESS"] == "host0:8476"
            assert envs["TPU_WORKER_HOSTNAMES"] == "host0,host1"
            assert envs["TPU_ACCELERATOR_TYPE"] == "v5p"
            assert spec["annotations"]["tpu.ktpu.io/plugin"] == "native"
            client.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    def test_kubelet_runs_tpu_pod_via_native_plugin(self, native_bins, tmp_path):
        """Full node path: C++ plugin socket discovered by the device manager,
        chips advertised in node status, pod admitted + env injected."""
        plugin_dir = tmp_path / "plugins"
        proc = start_native_plugin(native_bins["plugin"], plugin_dir, "v5e:4:sN:0")
        master = Master().start()
        cs = Clientset(master.url)
        sched = Scheduler(cs)
        sched.start()
        runtime = FakeRuntime()
        kubelet = Kubelet(
            cs, node_name="native-node", runtime=runtime,
            plugin_dir=str(plugin_dir), heartbeat_interval=0.5,
            sync_interval=0.2, pleg_interval=0.2,
        )
        kubelet.start()
        try:
            must_poll_until(
                lambda: len(
                    cs.nodes.get("native-node", "").status.extended_resources.get(
                        "google.com/tpu", []
                    )
                ) == 4,
                timeout=15.0, desc="native chips advertised",
            )
            pod = make_tpu_pod("native-tpu-pod", tpus=2)
            cs.pods.create(pod)
            must_poll_until(
                lambda: cs.pods.get("native-tpu-pod").status.phase == t.POD_RUNNING,
                timeout=20.0, desc="tpu pod running",
            )
            bound = cs.pods.get("native-tpu-pod")
            assert len(bound.spec.extended_resources[0].assigned) == 2
            # env injected by the native plugin made it into the container
            containers = runtime.list_containers()
            assert containers
        finally:
            kubelet.stop()
            sched.stop()
            cs.close()
            master.stop()
            proc.terminate()
            proc.wait(timeout=5)


class TestNativeExporter:
    def test_metrics_exposition(self, native_bins):
        env = dict(os.environ, KTPU_FAKE_TPUS="v5e:8:sliceM:0")
        proc = subprocess.Popen(
            [native_bins["exporter"], "--port", "0"],
            env=env, stdout=subprocess.PIPE, text=True,
        )
        try:
            line = proc.stdout.readline()
            port = int(line.strip().rsplit(":", 1)[1])
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            assert "ktpu_tpu_chips{" in text
            assert '} 8' in text.split("ktpu_tpu_chips{", 1)[1].split("\n", 1)[0]
            healthy_lines = [
                l for l in text.splitlines()
                if l.startswith("ktpu_tpu_chip_healthy{")
            ]
            assert len(healthy_lines) == 8
            assert all(l.endswith(" 1") for l in healthy_lines)
            assert 'slice="sliceM"' in healthy_lines[0]
            ok = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ).read().decode()
            assert ok.strip() == "ok"
        finally:
            proc.terminate()
            proc.wait(timeout=5)


class TestNativeCRIRuntime:
    """The C++ CRI runtime behind the unix-socket protocol must be driven
    by RemoteRuntime/kubelet exactly like the Python ProcessRuntime
    (kubelet/cri.py is the contract)."""

    @pytest.fixture
    def native_cri(self, native_bins, tmp_path):
        binary = os.path.join(NATIVE_DIR, "bin", "ktpu-cri-runtime")
        assert os.access(binary, os.X_OK)
        sock = str(tmp_path / "cri.sock")
        root = str(tmp_path / "rt")
        proc = subprocess.Popen([binary, "--socket", sock, "--root", root],
                                stderr=subprocess.PIPE, text=True)
        # pre-yield failures must still reap the spawned runtime: a bare
        # assert here would leak the process (r4's leaked-process lesson)
        try:
            deadline = time.monotonic() + 5
            while not os.path.exists(sock):
                assert proc.poll() is None, proc.stderr.read()
                assert time.monotonic() < deadline, "socket never appeared"
                time.sleep(0.05)
            from kubernetes1_tpu.kubelet.cri import RemoteRuntime

            client = RemoteRuntime(sock)
        except BaseException:
            proc.terminate()
            proc.wait(timeout=5)
            raise
        yield client, root
        client.close()
        proc.terminate()
        proc.wait(timeout=5)

    def test_capabilities_and_version(self, native_cri):
        client, root = native_cri
        assert client.real_pids is True
        assert client.root == root
        assert "ktpu-cri-runtime" in client.version()
        # the runtime's identity crosses the wire: the kubelet's
        # runAsNonRoot verification checks the RUNTIME's euid, not its own
        assert client.default_uid == os.geteuid()
        assert client.identity_known is True

    def test_real_process_lifecycle(self, native_cri, tmp_path):
        from kubernetes1_tpu.kubelet.runtime import (
            CONTAINER_EXITED,
            CONTAINER_RUNNING,
            ContainerConfig,
        )

        client, _ = native_cri
        sid = client.run_pod_sandbox("p", "default", "uid-1",
                                     labels={"pod-uid": "uid-1"})
        marker = str(tmp_path / "native-marker")
        cid = client.create_container(sid, ContainerConfig(
            name="c", image="img",
            command=["sh", "-c", f"echo from-native > {marker}; sleep 60"],
            env={"WHO": "native"}))
        client.start_container(cid)
        rec = client.container_status(cid)
        assert rec.state == CONTAINER_RUNNING
        deadline = time.monotonic() + 10
        while not os.path.exists(marker) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert os.path.exists(marker)
        # exec sees the container env
        code, out = client.exec_capture(cid, ["sh", "-c", "echo $WHO"])
        assert code == 0 and out.strip() == "native"
        client.stop_container(cid, timeout=2.0)
        rec = client.container_status(cid)
        assert rec.state == CONTAINER_EXITED
        client.stop_pod_sandbox(sid)
        client.remove_pod_sandbox(sid)
        assert client.list_pod_sandboxes() == []

    def test_exit_code_and_logs(self, native_cri):
        from kubernetes1_tpu.kubelet.runtime import (
            CONTAINER_EXITED,
            ContainerConfig,
        )

        client, _ = native_cri
        sid = client.run_pod_sandbox("p", "default", "uid-2")
        cid = client.create_container(sid, ContainerConfig(
            name="c", image="img",
            command=["sh", "-c", "echo line-one; echo line-two; exit 3"]))
        client.start_container(cid)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            rec = client.container_status(cid)
            if rec.state == CONTAINER_EXITED:
                break
            time.sleep(0.05)
        assert rec.state == CONTAINER_EXITED and rec.exit_code == 3
        log = client.read_log(cid)
        assert "line-one" in log and "line-two" in log
        assert client.read_log(cid, tail=1).strip() == "line-two"

    def test_kubelet_drives_native_runtime(self, native_cri):
        """Full kubelet sync loop -> C++ runtime -> real process."""
        from kubernetes1_tpu.kubelet.cri import RemoteRuntime

        client, _ = native_cri
        master = Master().start()
        cs = Clientset(master.url)
        kl = Kubelet(cs, node_name="native-node", runtime=client,
                     heartbeat_interval=1.0, sync_interval=0.2,
                     pleg_interval=0.2, server_port=None)
        kl.start()
        try:
            pod = t.Pod()
            pod.metadata.name = "on-native"
            pod.spec.node_name = "native-node"
            pod.spec.containers = [
                t.Container(name="c", image="img",
                            command=["sh", "-c", "sleep 60"])]
            cs.pods.create(pod)
            deadline = time.monotonic() + 20
            phase = None
            while time.monotonic() < deadline:
                p = cs.pods.get("on-native")
                phase = p.status.phase
                if phase == t.POD_RUNNING:
                    break
                time.sleep(0.2)
            assert phase == t.POD_RUNNING
        finally:
            kl.stop()
            cs.close()
            master.stop()

    def test_mounts_env_and_bind(self, native_cri, tmp_path):
        """Volume parity with ProcessRuntime: KTPU_VOLUME_<NAME> env always;
        bind mount at container_path when the host allows mount
        namespaces."""
        from kubernetes1_tpu.kubelet.runtime import ContainerConfig

        client, _ = native_cri
        vol = tmp_path / "voldata"
        vol.mkdir()
        (vol / "file.txt").write_text("from-volume")
        out_path = tmp_path / "copied"
        sid = client.run_pod_sandbox("p", "default", "uid-3")
        cid = client.create_container(sid, ContainerConfig(
            name="c", image="img",
            command=["sh", "-c",
                     'cp "$KTPU_VOLUME_DATA/file.txt" ' + str(out_path)
                     + "; sleep 0.1"],
            mounts=[{"name": "data", "host_path": str(vol),
                     "container_path": "/mnt/ktpu-test-data",
                     "read_only": False}]))
        client.start_container(cid)
        deadline = time.monotonic() + 10
        while not out_path.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert out_path.read_text() == "from-volume"

    def test_exec_refused_on_exited_and_stats_cpu(self, native_cri):
        from kubernetes1_tpu.kubelet.runtime import (
            CONTAINER_EXITED,
            ContainerConfig,
        )

        client, _ = native_cri
        sid = client.run_pod_sandbox("p", "default", "uid-4")
        # a busy-loop container: stats must report real cpu usage
        cid = client.create_container(sid, ContainerConfig(
            name="busy", image="img",
            command=["sh", "-c", "while true; do :; done"]))
        client.start_container(cid)
        time.sleep(0.3)
        client.container_stats(cid)  # first sample primes the rate
        time.sleep(0.5)
        stats = client.container_stats(cid)
        assert stats["cpu"] > 0.05
        assert stats["memory"] > 0
        client.stop_container(cid, timeout=1.0)
        rec = client.container_status(cid)
        assert rec.state == CONTAINER_EXITED
        # exec against an exited container is refused, not silently run
        code, out = client.exec_capture(cid, ["true"])
        assert code == -1 and "not running" in out

    def test_double_start_refused(self, native_cri):
        from kubernetes1_tpu.kubelet.runtime import ContainerConfig

        client, _ = native_cri
        sid = client.run_pod_sandbox("p", "default", "uid-5")
        cid = client.create_container(sid, ContainerConfig(
            name="c", image="img", command=["sleep", "30"]))
        client.start_container(cid)
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            client.start_container(cid)
        client.stop_container(cid, timeout=1.0)

    def test_remove_sandbox_kills_running_containers(self, native_cri):
        from kubernetes1_tpu.kubelet.runtime import ContainerConfig

        client, _ = native_cri
        sid = client.run_pod_sandbox("p", "default", "uid-6")
        cid = client.create_container(sid, ContainerConfig(
            name="c", image="img", command=["sleep", "300"]))
        client.start_container(cid)
        # find the real pid via exec
        code, out = client.exec_capture(cid, ["sh", "-c", "echo ok"])
        assert code == 0
        client.remove_pod_sandbox(sid)  # no explicit stop first
        assert client.list_pod_sandboxes() == []
        assert client.list_containers() == []

    def test_image_service_over_socket(self, native_cri):
        client, _ = native_cri
        assert client.images.image_present("jax-train") is False
        client.images.pull_image("jax-train")
        assert client.images.image_present("jax-train") is True
        assert "jax-train" in client.images.list_images()
