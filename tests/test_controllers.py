"""Controller integration tests: real apiserver + controller manager +
hollow kubelets (FakeRuntime) — the reference's test/integration suites
(deployment, job, garbagecollector) with the node side present so pods
actually run."""

import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.deviceplugin.api import PluginServer, plugin_socket_path
from kubernetes1_tpu.deviceplugin.tpu_plugin import (
    ANN_WORKER_ID,
    TPUDevicePlugin,
    _fake_devices,
)
from kubernetes1_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes1_tpu.machinery import NotFound
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod, mutate_with_retry


def start_hollow_node(cs, name, plugin_root, tpus=4, slice_id="s0", host_index=0,
                      tpu_type="v5e"):
    """Hollow kubelet + its own fake TPU plugin (kubemark pattern)."""
    plugin_dir = f"{plugin_root}/{name}"
    impl = TPUDevicePlugin(
        devices=_fake_devices(f"{tpu_type}:{tpus}:{slice_id}:{host_index}") if tpus else []
    )
    plugin = PluginServer(impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
    plugin.start()
    kubelet = Kubelet(
        cs,
        node_name=name,
        runtime=FakeRuntime(),
        plugin_dir=plugin_dir,
        heartbeat_interval=0.5,
        sync_interval=0.2,
        pleg_interval=0.2,
        capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
    )
    kubelet.start()
    return kubelet, plugin, impl


@pytest.fixture()
def cluster(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, gang_wait_seconds=5.0)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=2.0, eviction_timeout=2.0)
    cm.start()
    nodes = []
    for i in range(2):
        nodes.append(
            start_hollow_node(
                cs, f"host-{i}", str(tmp_path), tpus=4, slice_id="sliceA", host_index=i
            )
        )
    env = {"master": master, "cs": cs, "sched": sched, "cm": cm, "nodes": nodes,
           "tmp": tmp_path}
    yield env
    for kubelet, plugin, _ in nodes:
        kubelet.stop()
        plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def job_with(name, completions=None, parallelism=1, indexed=False, gang=False,
             tpus=0, exit_after=0.2, exit_code=0):
    job = t.Job()
    job.metadata.name = name
    c = t.Container(name="worker", image="jax-train", command=["sleep", str(exit_after)])
    c.env = [
        t.EnvVar(name="KTPU_FAKE_EXIT_AFTER", value=str(exit_after)),
        t.EnvVar(name="KTPU_FAKE_EXIT_CODE", value=str(exit_code)),
    ]
    if tpus:
        c.resources.limits = {"google.com/tpu": tpus}
    job.spec.template.spec.containers = [c]
    job.spec.completions = completions
    job.spec.parallelism = parallelism
    if indexed:
        job.spec.completion_mode = "Indexed"
    job.spec.gang_scheduling = gang
    return job


class TestJobController:
    def test_simple_job_completes(self, cluster):
        cs = cluster["cs"]
        cs.jobs.create(job_with("once", completions=1))
        must_poll_until(
            lambda: cs.jobs.get("once").status.succeeded >= 1,
            timeout=20.0,
            desc="job succeeded",
        )
        job = cs.jobs.get("once")
        assert any(c.type == "Complete" and c.status == "True" for c in job.status.conditions)

    def test_indexed_job_assigns_stable_indexes(self, cluster):
        cs = cluster["cs"]
        cs.jobs.create(job_with("idx", completions=3, parallelism=3, indexed=True))
        must_poll_until(
            lambda: cs.jobs.get("idx").status.completed_indexes == "0-2",
            timeout=25.0,
            desc="all indexes complete",
        )
        # pod names carry the index
        names = {f"idx-{i}" for i in range(3)}
        pods, _ = cs.pods.list(namespace="default", label_selector="batch.ktpu.io/job-name=idx")
        assert {p.metadata.name for p in pods} <= names | set()

    def test_indexed_tpu_job_gets_worker_env_annotations(self, cluster):
        cs = cluster["cs"]
        cs.jobs.create(
            job_with("tpu-idx", completions=2, parallelism=2, indexed=True, tpus=2,
                     exit_after=30)
        )
        must_poll_until(
            lambda: cs.jobs.get("tpu-idx").status.active == 2,
            timeout=20.0,
            desc="both workers active",
        )
        pods, _ = cs.pods.list(
            namespace="default", label_selector="batch.ktpu.io/job-name=tpu-idx"
        )
        by_name = {p.metadata.name: p for p in pods}
        assert by_name["tpu-idx-0"].metadata.annotations[ANN_WORKER_ID] == "0"
        assert by_name["tpu-idx-1"].metadata.annotations[ANN_WORKER_ID] == "1"
        assert "tpu-idx-0" in by_name["tpu-idx-1"].metadata.annotations[
            "tpu.ktpu.io/coordinator-address"
        ]
        for p in pods:
            assert len(p.spec.extended_resources[0].assigned) == 2
        cs.jobs.delete("tpu-idx")

    def test_gang_job_lands_on_one_slice(self, cluster):
        cs = cluster["cs"]
        cs.jobs.create(
            job_with("gang", completions=2, parallelism=2, indexed=True, tpus=4,
                     gang=True, exit_after=30)
        )
        must_poll_until(
            lambda: all(
                p.spec.node_name
                for p in cs.pods.list(
                    namespace="default",
                    label_selector="batch.ktpu.io/job-name=gang",
                )[0]
            )
            and len(
                cs.pods.list(
                    namespace="default", label_selector="batch.ktpu.io/job-name=gang"
                )[0]
            )
            == 2,
            timeout=20.0,
            desc="gang bound",
        )
        pods, _ = cs.pods.list(
            namespace="default", label_selector="batch.ktpu.io/job-name=gang"
        )
        assert {p.spec.node_name for p in pods} == {"host-0", "host-1"}
        for p in pods:
            assert p.spec.scheduling_gang
            assert p.spec.gang_size == 2
        cs.jobs.delete("gang")

    def test_elastic_restart_preserves_index(self, cluster):
        """Preemptible-slice behavior: a deleted worker is recreated with the
        same completion index (elastic restart)."""
        cs = cluster["cs"]
        cs.jobs.create(
            job_with("elastic", completions=2, parallelism=2, indexed=True,
                     exit_after=60)
        )
        must_poll_until(
            lambda: cs.jobs.get("elastic").status.active == 2,
            timeout=20.0,
            desc="both workers up",
        )
        uid_before = cs.pods.get("elastic-1").metadata.uid
        cs.pods.delete("elastic-1", grace_seconds=0)

        def recreated():
            try:
                return cs.pods.get("elastic-1").metadata.uid != uid_before
            except NotFound:
                return False

        must_poll_until(recreated, timeout=20.0, desc="index-1 worker recreated")
        assert (
            cs.pods.get("elastic-1").metadata.annotations[t.COMPLETION_INDEX_ANNOTATION]
            == "1"
        )
        cs.jobs.delete("elastic")

    def test_failed_job_backoff_limit(self, cluster):
        cs = cluster["cs"]
        job = job_with("failer", completions=1, exit_code=1)
        job.spec.backoff_limit = 1
        cs.jobs.create(job)
        must_poll_until(
            lambda: any(
                c.type == "Failed" and c.status == "True"
                for c in cs.jobs.get("failer").status.conditions
            ),
            timeout=30.0,
            desc="job marked Failed",
        )


class TestReplicaSetAndDeployment:
    def rs_spec(self, name, replicas):
        rs = t.ReplicaSet()
        rs.metadata.name = name
        rs.spec.replicas = replicas
        rs.spec.selector = t.LabelSelector(match_labels={"app": name})
        rs.spec.template.metadata.labels = {"app": name}
        rs.spec.template.spec.containers = [
            t.Container(name="web", image="web", command=["serve"])
        ]
        return rs

    def test_replicaset_scales_up_and_down(self, cluster):
        cs = cluster["cs"]
        cs.replicasets.create(self.rs_spec("web", 3))

        def count():
            pods, _ = cs.pods.list(namespace="default", label_selector="app=web")
            return len([p for p in pods if not p.metadata.deletion_timestamp])

        must_poll_until(lambda: count() == 3, timeout=15.0, desc="3 replicas")
        mutate_with_retry(cs.replicasets, "web", lambda rs: setattr(rs.spec, "replicas", 1))
        must_poll_until(lambda: count() == 1, timeout=15.0, desc="scaled to 1")
        cs.replicasets.delete("web")

    def test_deployment_rollout(self, cluster):
        cs = cluster["cs"]
        dep = t.Deployment()
        dep.metadata.name = "app"
        dep.spec.replicas = 2
        dep.spec.selector = t.LabelSelector(match_labels={"app": "app"})
        dep.spec.template.metadata.labels = {"app": "app"}
        dep.spec.template.spec.containers = [
            t.Container(name="c", image="v1", command=["serve"])
        ]
        cs.deployments.create(dep)
        must_poll_until(
            lambda: cs.deployments.get("app").status.ready_replicas == 2,
            timeout=20.0,
            desc="deployment ready",
        )
        # rollout: change image
        def set_v2(dep):
            dep.spec.template.spec.containers[0].image = "v2"

        mutate_with_retry(cs.deployments, "app", set_v2)

        def rolled():
            pods, _ = cs.pods.list(namespace="default", label_selector="app=app")
            imgs = {
                p.spec.containers[0].image
                for p in pods
                if not p.metadata.deletion_timestamp
                and p.status.phase == t.POD_RUNNING
            }
            return imgs == {"v2"} and len(pods) >= 2

        must_poll_until(rolled, timeout=30.0, desc="rolled to v2")
        cs.deployments.delete("app")


class TestDaemonSet:
    def test_one_pod_per_node(self, cluster):
        cs = cluster["cs"]
        ds = t.DaemonSet()
        ds.metadata.name = "exporter"
        ds.spec.selector = t.LabelSelector(match_labels={"app": "exporter"})
        ds.spec.template.metadata.labels = {"app": "exporter"}
        ds.spec.template.spec.containers = [
            t.Container(name="exp", image="tpu-metrics-exporter", command=["serve"])
        ]
        cs.daemonsets.create(ds)

        def placed():
            pods, _ = cs.pods.list(namespace="default", label_selector="app=exporter")
            return sorted(p.spec.node_name for p in pods) == ["host-0", "host-1"]

        must_poll_until(placed, timeout=15.0, desc="daemon pod per node")
        cs.daemonsets.delete("exporter")


class TestGarbageCollection:
    def test_orphans_deleted_with_owner(self, cluster):
        cs = cluster["cs"]
        cs.jobs.create(job_with("doomed", completions=1, exit_after=60))
        must_poll_until(
            lambda: len(
                cs.pods.list(
                    namespace="default", label_selector="batch.ktpu.io/job-name=doomed"
                )[0]
            )
            >= 1,
            timeout=15.0,
            desc="job pod created",
        )
        cs.jobs.delete("doomed")

        def cleaned():
            pods, _ = cs.pods.list(
                namespace="default", label_selector="batch.ktpu.io/job-name=doomed"
            )
            return len(pods) == 0

        must_poll_until(cleaned, timeout=20.0, desc="orphaned pods GCed")


class TestNamespaceLifecycle:
    def test_terminating_namespace_empties_and_finalizes(self, cluster):
        cs = cluster["cs"]
        pod = make_tpu_pod("ns-pod", tpus=0, ns="scratch")
        pod.spec.containers[0].command = ["sleep", "60"]
        cs.pods.create(pod, namespace="scratch")
        cs.namespaces.delete("scratch", "")

        def gone():
            try:
                cs.namespaces.get("scratch", "")
                return False
            except NotFound:
                return True

        must_poll_until(gone, timeout=20.0, desc="namespace finalized")


class TestNodeLifecycle:
    def test_dead_node_pods_evicted_and_rescheduled(self, cluster):
        """Failure detection -> eviction -> Job elastic recreate elsewhere."""
        cs = cluster["cs"]
        cs.jobs.create(
            job_with("survivor", completions=1, parallelism=1, exit_after=120)
        )
        must_poll_until(
            lambda: cs.jobs.get("survivor").status.active == 1,
            timeout=15.0,
            desc="worker up",
        )
        pods, _ = cs.pods.list(
            namespace="default", label_selector="batch.ktpu.io/job-name=survivor"
        )
        victim_node = pods[0].spec.node_name
        # kill that node's kubelet (heartbeat stops)
        for kubelet, plugin, _ in cluster["nodes"]:
            if kubelet.node_name == victim_node:
                kubelet.stop()

        def rescheduled():
            ps, _ = cs.pods.list(
                namespace="default", label_selector="batch.ktpu.io/job-name=survivor"
            )
            return any(
                p.spec.node_name and p.spec.node_name != victim_node for p in ps
            )

        must_poll_until(rescheduled, timeout=30.0, desc="worker re-formed on live node")
