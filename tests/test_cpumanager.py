"""CPU manager: topology-aware exclusive pinning + state checkpoint.

Ref: pkg/kubelet/cm/cpumanager/{cpu_manager,policy_static,cpu_assignment}.go
and state/state_file.go:45-119.
"""

import os

from kubernetes1_tpu.api import types as t
import pytest

from kubernetes1_tpu.kubelet.cpumanager import (
    POLICY_NONE,
    POLICY_STATIC,
    CPUExhaustedError,
    CPUManager,
    CPUTopology,
    take_by_topology,
)


def make_pod(uid, cpu=None, memory=None, name="p"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.uid = uid
    c = t.Container(name="main", image="img", command=["sleep", "1"])
    if cpu is not None:
        c.resources.limits = {"cpu": cpu, **({"memory": memory} if memory else {})}
        c.resources.requests = dict(c.resources.limits)
    pod.spec.containers = [c]
    return pod


def guaranteed_pod(uid, cpu="2"):
    return make_pod(uid, cpu=cpu, memory="64Mi")


class TestTopology:
    def test_synthetic_layout(self):
        topo = CPUTopology.synthetic(2, 4, 2)  # 2 sockets x 4 cores x 2 threads
        assert topo.num_cpus == 16
        assert len(topo.cpus_per_core()) == 8
        assert len(topo.cpus_per_socket()) == 2

    def test_discover_falls_back_flat(self, tmp_path):
        topo = CPUTopology.discover(sysfs=str(tmp_path / "missing"))
        assert topo.num_cpus == (os.cpu_count() or 1)

    def test_take_prefers_whole_cores(self):
        topo = CPUTopology.synthetic(1, 4, 2)
        got = take_by_topology(topo, set(range(8)), 2)
        # 2 cpus should be the two threads of ONE physical core
        cores = {topo.cpus[c].core for c in got}
        assert len(cores) == 1

    def test_take_prefers_whole_socket(self):
        topo = CPUTopology.synthetic(2, 2, 2)  # sockets of 4 cpus
        got = take_by_topology(topo, set(range(8)), 4)
        sockets = {topo.cpus[c].socket for c in got}
        assert len(sockets) == 1

    def test_take_leftover_threads_prefer_partial_cores(self):
        topo = CPUTopology.synthetic(1, 2, 2)
        # cpu 1 (thread of core 0) taken -> available 0,2,3; want 1
        got = take_by_topology(topo, {0, 2, 3}, 1)
        # should pick cpu 0 (its core already broken) keeping core 1 intact
        assert got == {0}

    def test_take_insufficient_raises(self):
        topo = CPUTopology.synthetic(1, 1, 2)
        try:
            take_by_topology(topo, {0}, 2)
            raise AssertionError("expected ValueError")
        except ValueError:
            pass


class TestStaticPolicy:
    def mgr(self, tmp_path, sockets=1, cores=4, threads=2):
        return CPUManager(
            policy=POLICY_STATIC,
            topology=CPUTopology.synthetic(sockets, cores, threads),
            state_path=str(tmp_path / "cpu_manager_state.json"),
        )

    def test_guaranteed_integer_gets_exclusive(self, tmp_path):
        m = self.mgr(tmp_path)
        pod = guaranteed_pod("u1", cpu="2")
        got = m.cpuset_for_container(pod, pod.spec.containers[0])
        assert len(got) == 2
        # removed from the shared pool
        assert not (got & m.state.default_cpuset)

    def test_burstable_gets_shared_pool(self, tmp_path):
        m = self.mgr(tmp_path)
        gpod = guaranteed_pod("u1", cpu="2")
        excl = m.cpuset_for_container(gpod, gpod.spec.containers[0])
        bpod = make_pod("u2", cpu="500m")  # fractional -> not exclusive
        shared = m.cpuset_for_container(bpod, bpod.spec.containers[0])
        assert shared == m.state.default_cpuset
        assert not (shared & excl)

    def test_fractional_guaranteed_not_exclusive(self, tmp_path):
        m = self.mgr(tmp_path)
        pod = guaranteed_pod("u1", cpu="1500m")
        got = m.cpuset_for_container(pod, pod.spec.containers[0])
        assert got == m.state.default_cpuset

    def test_release_returns_cpus(self, tmp_path):
        m = self.mgr(tmp_path)
        pod = guaranteed_pod("u1", cpu="4")
        got = m.cpuset_for_container(pod, pod.spec.containers[0])
        assert len(got) == 4
        m.release_pod("u1")
        assert m.state.default_cpuset == {c.cpu for c in m.topology.cpus}

    def test_same_container_stable_assignment(self, tmp_path):
        m = self.mgr(tmp_path)
        pod = guaranteed_pod("u1", cpu="2")
        a = m.cpuset_for_container(pod, pod.spec.containers[0])
        b = m.cpuset_for_container(pod, pod.spec.containers[0])
        assert a == b

    def test_exhaustion_fails_container(self, tmp_path):
        # ref policy_static.go: exclusive exhaustion is an allocation ERROR,
        # never a silent fallback onto someone else's exclusive cores
        m = self.mgr(tmp_path, sockets=1, cores=2, threads=1)  # 2 cpus, 1 reserved
        p1 = guaranteed_pod("u1", cpu="1")
        assert m.cpuset_for_container(p1, p1.spec.containers[0]) == {1}
        p2 = guaranteed_pod("u2", cpu="1")
        with pytest.raises(CPUExhaustedError):
            m.cpuset_for_container(p2, p2.spec.containers[0])
        # non-exclusive containers still land on the reserved shared pool
        bpod = make_pod("u3", cpu="500m")
        assert m.cpuset_for_container(bpod, bpod.spec.containers[0]) == {0}

    def test_default_reserve_keeps_one_cpu_shared(self, tmp_path):
        # static policy defaults to reserving cpu 0 (upstream mandates a
        # nonzero system reserve) so the shared pool can never fully drain
        m = self.mgr(tmp_path)  # 8 cpus
        p1 = guaranteed_pod("u1", cpu="7")
        got = m.cpuset_for_container(p1, p1.spec.containers[0])
        assert len(got) == 7 and 0 not in got

    def test_checkpoint_survives_restart(self, tmp_path):
        m = self.mgr(tmp_path)
        pod = guaranteed_pod("u1", cpu="2")
        got = m.cpuset_for_container(pod, pod.spec.containers[0])
        # new manager over the same state file: assignment restored
        m2 = self.mgr(tmp_path)
        assert m2.state.entries["u1/main"] == got
        assert not (got & m2.state.default_cpuset)

    def test_reconcile_drops_stale_pods(self, tmp_path):
        m = self.mgr(tmp_path)
        pod = guaranteed_pod("u1", cpu="2")
        m.cpuset_for_container(pod, pod.spec.containers[0])
        m.reconcile(live_uids={"other"})
        assert "u1/main" not in m.state.entries
        assert m.state.default_cpuset == {c.cpu for c in m.topology.cpus}

    def test_reserved_cpus_never_exclusive(self, tmp_path):
        m = CPUManager(
            policy=POLICY_STATIC,
            topology=CPUTopology.synthetic(1, 4, 1),
            state_path=str(tmp_path / "s.json"),
            reserved_cpus=2,
        )
        pod = guaranteed_pod("u1", cpu="2")
        got = m.cpuset_for_container(pod, pod.spec.containers[0])
        assert not (got & {0, 1})

    def test_none_policy_disabled(self, tmp_path):
        m = CPUManager(policy=POLICY_NONE,
                       topology=CPUTopology.synthetic(1, 4, 2))
        pod = guaranteed_pod("u1", cpu="2")
        assert m.cpuset_for_container(pod, pod.spec.containers[0]) is None


class TestRuntimeWrap:
    def test_wrap_with_cpuset_uses_taskset(self):
        from kubernetes1_tpu.kubelet import runtime as rt

        cmd = rt._wrap_with_cpuset(["sleep", "1"], [2, 0])
        if rt._TASKSET:
            assert cmd[1:3] == ["-c", "0,2"]
            assert cmd[3:] == ["sleep", "1"]
        else:
            assert cmd == ["sleep", "1"]


class TestPoolChangeRepin:
    def test_empty_pool_falls_back_to_reserved_or_none(self, tmp_path):
        # explicit reserved_cpus=0 is the escape hatch that allows a fully
        # drained shared pool; the lookup then answers None (pin nowhere is
        # better than an empty-set no-op that unpins from everything)
        m = CPUManager(policy=POLICY_STATIC,
                       topology=CPUTopology.synthetic(1, 2, 1),
                       state_path=str(tmp_path / "s.json"),
                       reserved_cpus=0)
        p1 = guaranteed_pod("u1", cpu="2")
        m.cpuset_for_container(p1, p1.spec.containers[0])
        bpod = make_pod("u2", cpu="500m")
        assert m.cpuset_for_container(bpod, bpod.spec.containers[0]) is None

        m2 = CPUManager(policy=POLICY_STATIC,
                        topology=CPUTopology.synthetic(1, 3, 1),
                        state_path=str(tmp_path / "s2.json"),
                        reserved_cpus=1)
        p2 = guaranteed_pod("u3", cpu="2")
        m2.cpuset_for_container(p2, p2.spec.containers[0])
        got = m2.cpuset_for_container(bpod, bpod.spec.containers[0])
        assert got == {0}  # the reserved cpu

    def test_on_pool_change_fires_on_grant_and_release(self, tmp_path):
        events = []
        m = CPUManager(policy=POLICY_STATIC,
                       topology=CPUTopology.synthetic(1, 4, 1),
                       state_path=str(tmp_path / "s.json"))
        m.on_pool_change = lambda: events.append("changed")
        pod = guaranteed_pod("u1", cpu="2")
        m.cpuset_for_container(pod, pod.spec.containers[0])
        assert events == ["changed"]
        m.release_pod("u1")
        assert events == ["changed", "changed"]
        # shared lookup does not fire
        bpod = make_pod("u2", cpu="500m")
        m.cpuset_for_container(bpod, bpod.spec.containers[0])
        assert len(events) == 2

    def test_none_policy_skips_discovery_and_state(self, tmp_path):
        state = tmp_path / "never.json"
        m = CPUManager(policy=POLICY_NONE, state_path=str(state))
        assert not state.exists()
        assert m.topology.num_cpus == 0


class TestAffinityRepin:
    def test_process_runtime_repins_live_tree(self, tmp_path):
        import time as _t

        from kubernetes1_tpu.kubelet.runtime import (
            CONTAINER_RUNNING,
            ContainerConfig,
            ProcessRuntime,
        )

        rt = ProcessRuntime(root_dir=str(tmp_path))
        sid = rt.run_pod_sandbox("p", "default", "u1")
        cid = rt.create_container(
            sid, ContainerConfig(name="c", image="i",
                                 command=["sleep", "30"]))
        rt.start_container(cid)
        assert rt.container_status(cid).state == CONTAINER_RUNNING
        avail = sorted(os.sched_getaffinity(0))
        ok = rt.set_container_affinity(cid, set(avail[:1]))
        assert ok
        proc = rt._procs[cid]
        assert os.sched_getaffinity(proc.pid) == set(avail[:1])
        rt.stop_container(cid, timeout=1.0)

    def test_remote_runtime_proxies_capabilities_and_affinity(self, tmp_path):
        from kubernetes1_tpu.kubelet.cri import RemoteRuntime, RuntimeServer
        from kubernetes1_tpu.kubelet.runtime import (
            ContainerConfig,
            ProcessRuntime,
        )

        backend = ProcessRuntime(root_dir=str(tmp_path / "rt"))
        server = RuntimeServer(backend, str(tmp_path / "cri.sock")).start()
        client = RemoteRuntime(server.socket_path)
        try:
            assert client.real_pids is True
            sid = client.run_pod_sandbox("p", "default", "u1")
            cid = client.create_container(
                sid, ContainerConfig(name="c", image="i",
                                     command=["sleep", "30"]))
            client.start_container(cid)
            avail = sorted(os.sched_getaffinity(0))
            assert client.set_container_affinity(cid, set(avail[:1]))
            client.stop_container(cid, timeout=1.0)
        finally:
            client.close()
            server.stop()
