"""Sharded store + multi-apiserver scale-out (storage/shardmap.py).

Covers the revision contract (stride-encoded per-shard revisions,
composite resourceVersions, bookmark resume), the ShardedStore /
ShardedCacher facades (routing, cross-shard LIST merge, merged
multi-shard watch with strict PER-SHARD order under concurrent
group commits), the shards=1 byte-identical equivalence, informer
relist convergence when one shard 410-evicts, N apiservers over one
shard set, and the bindings:batch body-codec fast path.
"""

import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery import Conflict, TooOldResourceVersion
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import (
    Cacher,
    ShardMap,
    ShardedCacher,
    ShardedStore,
    Store,
    build_sharded_store,
    format_rv,
    parse_rv,
    parse_shard_addresses,
)


def _cm(name, ns="default", **data):
    cm = t.ConfigMap(data={k: str(v) for k, v in data.items()})
    cm.metadata.name = name
    cm.metadata.namespace = ns
    return cm


def _key(name, ns="default"):
    return f"/registry/configmaps/{ns}/{name}"


def _rev(obj) -> int:
    return int(obj.metadata.resource_version)


class TestShardMapAndRv:
    def test_shard_of_key_deterministic_and_in_range(self):
        m = ShardMap(4)
        keys = [_key(f"x{i}") for i in range(200)]
        shards = [m.shard_of_key(k) for k in keys]
        assert shards == [m.shard_of_key(k) for k in keys]
        assert set(shards) <= set(range(4))
        # a 200-key spray should touch every shard (crc32 spreads)
        assert len(set(shards)) == 4

    def test_single_shard_short_circuits(self):
        m = ShardMap(1)
        assert m.shard_of_key("/registry/pods/default/x") == 0

    def test_rv_round_trip(self):
        assert parse_rv("17") == 17
        assert parse_rv("") == 0
        assert parse_rv(None) == 0
        assert parse_rv(42) == 42
        assert parse_rv("3.17.22") == (3, 17, 22)
        assert format_rv([3, 17, 22]) == "3.17.22"
        assert parse_rv(format_rv([5])) == 5  # 1 shard collapses to int
        with pytest.raises(ValueError):
            parse_rv("abc")

    def test_parse_shard_addresses(self):
        assert parse_shard_addresses("a.sock") == ["a.sock"]
        assert parse_shard_addresses("a,b; c,d ;e") == ["a,b", "c,d", "e"]


class TestStrideRevisions:
    def test_default_sequence_unchanged(self):
        st = Store(global_scheme.copy())
        revs = [_rev(st.create(_key(f"a{i}"), _cm(f"a{i}")))
                for i in range(3)]
        assert revs == [1, 2, 3]
        st.close()

    def test_stride_residue_class(self):
        for i in range(3):
            st = Store(global_scheme.copy(), rev_offset=i, rev_stride=3)
            revs = [_rev(st.create(_key(f"b{k}"), _cm(f"b{k}")))
                    for k in range(4)]
            assert revs == [i + 3, i + 6, i + 9, i + 12]
            assert all(r % 3 == i for r in revs)
            st.close()

    def test_bad_offset_rejected(self):
        with pytest.raises(ValueError):
            Store(global_scheme.copy(), rev_offset=3, rev_stride=3)
        with pytest.raises(ValueError):
            Store(global_scheme.copy(), rev_offset=-1, rev_stride=2)

    def test_wal_replay_keeps_residue(self, tmp_path):
        wal = str(tmp_path / "s1.wal")
        st = Store(global_scheme.copy(), wal_path=wal,
                   rev_offset=1, rev_stride=2)
        st.create(_key("w0"), _cm("w0"))
        st.create(_key("w1"), _cm("w1"))
        st.close()
        re = Store(global_scheme.copy(), wal_path=wal,
                   rev_offset=1, rev_stride=2)
        assert re.current_revision() == 5  # 3 then 5
        assert _rev(re.create(_key("w2"), _cm("w2"))) == 7  # stride continues
        re.close()


class TestShardedStoreOps:
    def setup_method(self):
        self.st = build_sharded_store(global_scheme.copy, 3)

    def teardown_method(self):
        self.st.close()

    def _fill(self, n=12):
        return {f"c{i}": self.st.create(_key(f"c{i}"), _cm(f"c{i}", i=i))
                for i in range(n)}

    def test_crud_routes_and_unique_revs(self):
        objs = self._fill()
        revs = sorted(_rev(o) for o in objs.values())
        assert len(set(revs)) == len(revs)  # globally unique
        got = self.st.get(_key("c3"))
        assert got.data["i"] == "3"
        got.data["i"] = "33"
        updated = self.st.update_cas(_key("c3"), got)
        assert self.st.get(_key("c3")).data["i"] == "33"
        assert _rev(updated) % 3 == self.st.map.shard_of_key(_key("c3"))
        self.st.delete(_key("c3"))
        assert self.st.get_or_none(_key("c3")) is None

    def test_list_merge_sorted_with_composite_rv(self):
        self._fill()
        entries, rv = self.st.list_raw("/registry/configmaps/")
        keys = [k for k, _r, _o in entries]
        assert keys == sorted(keys) and len(keys) == 12
        parts = parse_rv(rv)
        assert isinstance(parts, tuple) and len(parts) == 3
        for i, p in enumerate(parts):
            assert p % 3 == i  # each part is its own shard's revision
        objs, rv2 = self.st.list("/registry/configmaps/")
        assert len(objs) == 12 and rv2 == rv

    def test_get_raw_many_preserves_order(self):
        self._fill()
        keys = [_key("c5"), _key("missing"), _key("c0"), _key("c11")]
        raws = self.st.get_raw_many(keys)
        assert raws[1] is None
        assert raws[0]["data"]["i"] == "5"
        assert raws[2]["data"]["i"] == "0"
        assert raws[3]["data"]["i"] == "11"

    def test_commit_batch_cross_shard_outcomes(self):
        objs = self._fill(6)
        scheme = global_scheme.copy()
        ops = []
        for i in range(6):
            enc = scheme.encode(objs[f"c{i}"])
            enc["data"]["i"] = str(100 + i)
            ops.append({"op": "update_cas", "key": _key(f"c{i}"),
                        "obj": enc,
                        "expect_rv": objs[f"c{i}"].metadata.resource_version})
        # one doomed op: stale rv -> per-op Conflict, neighbors commit
        ops[2]["expect_rv"] = "999999"
        outs = self.st.commit_batch(ops)
        assert len(outs) == 6
        assert isinstance(outs[2]["error"], Conflict)
        for i in (0, 1, 3, 4, 5):
            assert outs[i]["obj"]["data"]["i"] == str(100 + i)
        assert self.st.get(_key("c2")).data["i"] == "2"  # untouched

    def test_guaranteed_update_routes(self):
        self._fill(3)

        def bump(cur):
            cur.data["i"] = "bumped"
            return cur

        self.st.guaranteed_update(_key("c1"), bump)
        assert self.st.get(_key("c1")).data["i"] == "bumped"


class TestMergedWatch:
    def setup_method(self):
        self.st = build_sharded_store(global_scheme.copy, 3)

    def teardown_method(self):
        self.st.close()

    def test_per_shard_order_under_concurrent_commits(self):
        w = self.st.watch("/registry/")
        stop = threading.Event()

        def writer(wid):
            for i in range(40):
                self.st.create(_key(f"t{wid}-{i}"), _cm(f"t{wid}-{i}"))

        threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stop.set()
        seen = []
        while len(seen) < 160:
            batch = w.next_batch_timeout(2.0)
            assert batch is not None, f"merged watch stalled at {len(seen)}"
            seen.extend(batch)
        last = [0, 0, 0]
        for ev in seen:
            rv = int(ev.object["metadata"]["resourceVersion"])
            assert rv > last[rv % 3], "per-shard revision order violated"
            last[rv % 3] = rv
        w.stop()

    def test_composite_resume_exact(self):
        for i in range(9):
            self.st.create(_key(f"r{i}"), _cm(f"r{i}"))
        _entries, rv = self.st.list_raw("/registry/configmaps/")
        for i in range(9, 15):
            self.st.create(_key(f"r{i}"), _cm(f"r{i}"))
        w = self.st.watch("/registry/", since_rev=parse_rv(rv))
        names = set()
        while len(names) < 6:
            batch = w.next_batch_timeout(2.0)
            assert batch is not None, f"resume stalled at {sorted(names)}"
            names |= {ev.object["metadata"]["name"] for ev in batch}
        # exactly the post-list creates: no duplicates from before the rv
        assert names == {f"r{i}" for i in range(9, 15)}
        w.stop()

    def test_replay_all_from_tiny_rev(self):
        for i in range(8):
            self.st.create(_key(f"p{i}"), _cm(f"p{i}"))
        w = self.st.watch("/registry/", since_rev=1)
        names = set()
        while len(names) < 8:
            batch = w.next_batch_timeout(2.0)
            assert batch is not None
            names |= {ev.object["metadata"]["name"] for ev in batch}
        assert names == {f"p{i}" for i in range(8)}
        w.stop()

    def test_bookmark_positions_advance(self):
        w = self.st.watch("/registry/")
        assert w.emit_bookmarks  # 3 shards: merged stream bookmarks
        for i in range(6):
            self.st.create(_key(f"bm{i}"), _cm(f"bm{i}"))
        got = 0
        while got < 6:
            batch = w.next_batch_timeout(2.0)
            assert batch is not None
            got += len(batch)
        parts = parse_rv(w.bookmark_rv())
        assert isinstance(parts, tuple) and len(parts) == 3
        # resuming from the bookmark replays nothing already delivered
        w2 = self.st.watch("/registry/", since_rev=parts)
        assert w2.next_batch_timeout(0.3) is None
        w.stop()
        w2.stop()

    def test_empty_shard_zero_floor_does_not_gap(self):
        """Regression: an empty shard 0 mints composite part 0 (its
        revisions live in the 0 residue class); resuming that part as
        from-now gapped anything committed on shard 0 between the LIST
        and the watch registration — part 0 must replay everything."""
        # list while shard 0 has nothing: its part is the 0 floor
        names, attempts = [], 0
        while True:
            _entries, rv = self.st.list_raw("/registry/configmaps/")
            parts = parse_rv(rv)
            if parts[0] == 0:
                break
            assert attempts == 0, "shard 0 unexpectedly non-empty"
            break
        assert parts[0] == 0
        # now commit a spray; some keys land on shard 0
        for i in range(24):
            self.st.create(_key(f"g{i}"), _cm(f"g{i}"))
        on_shard0 = [f"g{i}" for i in range(24)
                     if self.st.map.shard_of_key(_key(f"g{i}")) == 0]
        assert on_shard0, "spray never hit shard 0; widen it"
        w = self.st.watch("/registry/", since_rev=parts)
        got = set()
        while len(got) < 24:
            batch = w.next_batch_timeout(2.0)
            assert batch is not None, f"gapped at {sorted(got)}"
            got |= {ev.object["metadata"]["name"] for ev in batch}
        assert set(on_shard0) <= got  # nothing on shard 0 was gapped
        w.stop()

    def test_composite_arity_mismatch_410s(self):
        with pytest.raises(TooOldResourceVersion):
            self.st.watch("/registry/", since_rev=(1, 2))  # 2 parts, 3 shards

    def test_slow_consumer_evicted_once(self):
        w = self.st.watch("/registry/", queue_limit=8)
        for i in range(40):
            self.st.create(_key(f"ev{i}"), _cm(f"ev{i}"))
        # never drained: the shared bound trips no matter which shard pushed
        deadline = time.monotonic() + 5.0
        while not w.evicted and time.monotonic() < deadline:
            time.sleep(0.02)
        assert w.evicted
        assert self.st.watch_evictions >= 1


class TestShardsOneEquivalence:
    """shards=1 must stay byte-identical to the unsharded store: same
    revision sequence, same wire frames, plain-int resourceVersions, no
    bookmark frames."""

    def _drive(self, store, cacher, scheme):
        frames = []
        w = cacher.watch("/registry/", since_rev=0)
        for i in range(5):
            cm = _cm(f"e{i}", i=i)
            cm.metadata.uid = f"uid-e{i}"  # deterministic: frames compare
            store.create(_key(f"e{i}"), cm)
        store.delete(_key("e2"))
        got = 0
        while got < 6:
            batch = w.next_batch_timeout(2.0)
            assert batch is not None
            for ev in batch:
                frames.append(scheme.watch_frame_bytes(ev.type, ev.object))
                got += 1
        w.stop()
        entries, rv = cacher.list_raw("/registry/configmaps/")
        body = [scheme.encode_bytes(obj) for _k, _r, obj in entries]
        return frames, body, str(rv)

    def test_wire_frames_identical(self):
        plain_scheme = global_scheme.copy()
        plain_store = Store(plain_scheme)
        plain_cacher = Cacher(plain_store, plain_scheme).start()
        sh_scheme = global_scheme.copy()
        sharded = ShardedStore([Store(sh_scheme)])
        sh_cacher = ShardedCacher(sharded, sh_scheme).start()
        try:
            pf, pb, prv = self._drive(plain_store, plain_cacher, plain_scheme)
            sf, sb, srv = self._drive(sharded, sh_cacher, sh_scheme)
            assert pf == sf  # watch frames byte-identical
            assert pb == sb  # list bodies byte-identical
            assert prv == srv  # plain int rv, no composite dots
            assert "." not in srv
        finally:
            plain_cacher.stop()
            sh_cacher.stop()
            plain_store.close()
            sharded.close()

    def test_one_shard_stream_never_bookmarks(self):
        scheme = global_scheme.copy()
        sharded = ShardedStore([Store(scheme)])
        w = sharded.watch("/registry/")
        assert not w.emit_bookmarks
        w.stop()
        sharded.close()

    def test_master_default_path_is_plain(self):
        from kubernetes1_tpu.apiserver import Master

        m = Master().start()
        try:
            assert isinstance(m.store, Store)  # no facade in the default path
            assert m.store_shards == 1
        finally:
            m.stop()


@pytest.mark.thread_leak_ok  # full apiserver topology
class TestShardedMasterE2E:
    def test_http_list_watch_and_informer_shard_evict(self):
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset, SharedInformer

        m = Master(store_shards=3).start()
        cs = Clientset(m.url)
        try:
            for i in range(9):
                cs.configmaps.create(_cm(f"m{i}", i=i), "default")
            items, rv = cs.configmaps.list(namespace="default")
            assert len(items) == 9
            assert isinstance(parse_rv(rv), tuple)

            inf = SharedInformer(cs.configmaps, namespace="default")
            inf.start()
            assert inf.wait_for_sync(10.0)
            for i in range(9, 12):
                cs.configmaps.create(_cm(f"m{i}", i=i), "default")

            def have(n):
                return len(inf.list()) >= n

            deadline = time.monotonic() + 10
            while not have(12) and time.monotonic() < deadline:
                time.sleep(0.05)
            assert have(12)

            # one shard 410-evicts the fan-in watcher: the merged stream
            # must end with 410 and the informer must RELIST and converge
            # (the cross-shard eviction contract — a stream missing one
            # shard can never again be gap-free)
            relists_before = inf.relists
            evicted = 0
            for c in m.cacher.shard_cachers:
                with c._cond:
                    for w in list(c._watchers):
                        w._evict()
                        evicted += 1
                break  # ONE shard's cacher evicts
            assert evicted >= 1
            for i in range(12, 15):
                cs.configmaps.create(_cm(f"m{i}", i=i), "default")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                names = {o.metadata.name for o in inf.list()}
                if {f"m{i}" for i in range(15)} <= names \
                        and inf.relists > relists_before:
                    break
                time.sleep(0.1)
            names = {o.metadata.name for o in inf.list()}
            assert {f"m{i}" for i in range(15)} <= names
            assert inf.relists > relists_before
            inf.stop()
        finally:
            cs.close()
            m.stop()

    def test_watch_stream_carries_bookmarks(self):
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset
        from kubernetes1_tpu.client.rest import ApiClient

        m = Master(store_shards=2).start()
        cs = Clientset(m.url)
        api = ApiClient(m.url)
        try:
            cs.configmaps.create(_cm("seed"), "default")
            _items, rv = cs.configmaps.list(namespace="default")
            seen = {"bookmarks": [], "events": []}
            done = threading.Event()

            def wl():
                with api.watch("/api/v1/namespaces/default/configmaps",
                               {"resourceVersion": str(rv)}) as s:
                    for et, obj in s:
                        if et == "BOOKMARK":
                            seen["bookmarks"].append(
                                obj["metadata"]["resourceVersion"])
                        else:
                            seen["events"].append(obj["metadata"]["name"])
                        if len(seen["events"]) >= 3 and seen["bookmarks"]:
                            done.set()
                            return

            th = threading.Thread(target=wl, daemon=True)
            th.start()
            time.sleep(0.2)
            for i in range(3):
                cs.configmaps.create(_cm(f"bk{i}"), "default")
            assert done.wait(10.0), seen
            assert seen["events"] == [f"bk{i}" for i in range(3)]
            # bookmarks are composite resume positions for the shard set
            assert all(isinstance(parse_rv(b), tuple)
                       for b in seen["bookmarks"])
        finally:
            api.close()
            cs.close()
            m.stop()


@pytest.mark.thread_leak_ok  # two apiservers + two store servers
class TestMultiApiserver:
    def test_two_apiservers_over_one_shard_set(self, tmp_path):
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset, SharedInformer
        from kubernetes1_tpu.storage.server import StoreServer

        socks, servers = [], []
        for i in range(2):
            st = Store(global_scheme.copy(), rev_offset=i, rev_stride=2)
            sock = str(tmp_path / f"shard{i}.sock")
            servers.append(StoreServer(st, sock).start())
            socks.append(sock)
        addr = ";".join(socks)
        a = Master(store_address=addr).start()
        b = Master(store_address=addr).start()
        cs_a = Clientset(a.url)
        cs_b = Clientset(b.url)
        inf = None
        try:
            assert a.store_shards == 2 and b.store_shards == 2
            # writes through A are readable through B (store-fallback on
            # a cache miss covers the peer-write freshness window)
            for i in range(6):
                cs_a.configmaps.create(_cm(f"ha{i}", i=i), "default")
            for i in range(6):
                got = cs_b.configmaps.get(f"ha{i}", namespace="default")
                assert got.data["i"] == str(i)
            items_b, rv_b = cs_b.configmaps.list(namespace="default")
            assert len(items_b) == 6
            assert isinstance(parse_rv(rv_b), tuple)
            # an informer on B converges on writes through A
            inf = SharedInformer(cs_b.configmaps, namespace="default")
            inf.start()
            assert inf.wait_for_sync(10.0)
            for i in range(6, 9):
                cs_a.configmaps.create(_cm(f"ha{i}", i=i), "default")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if {o.metadata.name for o in inf.list()} >= \
                        {f"ha{i}" for i in range(9)}:
                    break
                time.sleep(0.1)
            assert {o.metadata.name for o in inf.list()} >= \
                {f"ha{i}" for i in range(9)}
        finally:
            if inf is not None:
                inf.stop()
            cs_a.close()
            cs_b.close()
            a.stop()
            b.stop()
            for s in servers:
                s.stop()


@pytest.mark.thread_leak_ok
class TestBindBatchCodec:
    """The scheduler→apiserver hot bind leg: bindings:batch with a
    pre-encoded spliced JSON body (always) or a pybin1 codec payload
    (--bind-codec), over the client's persistent connection."""

    def _bound_batch(self, m, codec):
        from kubernetes1_tpu.client import Clientset
        from tests.helpers import make_node, make_tpu_pod

        cs = Clientset(m.url, bind_codec=codec)
        try:
            cs.nodes.create(make_node(f"bn-{codec}", cpu="64",
                                      memory="64Gi", tpus=8,
                                      slice_id=f"bs-{codec}", host_index=0))
            bindings = []
            for i in range(4):
                name = f"bc-{codec}-{i}"
                cs.pods.create(make_tpu_pod(name, tpus=1))
                b = t.Binding(
                    target_node=f"bn-{codec}",
                    extended_resource_assignments={
                        f"{name}-tpu": [f"bs-{codec}-h0-tpu{i}"]})
                b.metadata.name = name
                b.metadata.namespace = "default"
                bindings.append(b)
            outcomes = cs.bind_batch("default", bindings)
            assert outcomes == [None] * 4, outcomes
            for i in range(4):
                p = cs.pods.get(f"bc-{codec}-{i}")
                assert p.spec.node_name == f"bn-{codec}"
                assert p.spec.extended_resources[0].assigned == \
                    [f"bs-{codec}-h0-tpu{i}"]
        finally:
            cs.close()

    def test_json_spliced_and_pybin1_bodies(self):
        from kubernetes1_tpu.apiserver import Master

        m = Master(store_shards=2).start()
        try:
            self._bound_batch(m, "json")
            self._bound_batch(m, "pybin1")
        finally:
            m.stop()

    def test_unknown_codec_content_type_400s(self):
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client.rest import ApiClient
        from kubernetes1_tpu.machinery import ApiError

        m = Master().start()
        api = ApiClient(m.url)
        try:
            with pytest.raises(ApiError) as ei:
                api.request("POST",
                            "/api/v1/namespaces/default/configmaps",
                            body=b"\x00\x01",
                            content_type="application/x-ktpu-nope")
            assert ei.value.code == 400
        finally:
            api.close()
            m.stop()

    def test_codec_fallback_sticks_after_400(self):
        from kubernetes1_tpu.client import Clientset
        from kubernetes1_tpu.machinery import ApiError

        cs = Clientset("http://127.0.0.1:1", bind_codec="pybin1")
        calls = []

        def fake_request(method, path, body=None, params=None, raw=False,
                         content_type=""):
            calls.append(content_type)
            if content_type:
                err = ApiError("unsupported content type")
                err.code = 400
                raise err
            return {"results": [{"status": "Success"}]}

        cs.api.request = fake_request
        b = t.Binding(target_node="n")
        b.metadata.name = "p"
        b.metadata.namespace = "default"
        assert cs.bind_batch("default", [b]) == [None]
        assert calls == ["application/x-ktpu-pybin1", ""]
        # the fallback is sticky: no re-probe on the next batch
        assert cs.bind_batch("default", [b]) == [None]
        assert calls[-1] == "" and len(calls) == 3
        cs.close()
