"""Serving data plane: continuous batching, least-inflight routing, and
zero-downtime rollout.

Three layers, cheapest first:
- batching units (jax on the virtual CPU mesh): batched decode must be
  token-identical to the sequential baseline, slot admission must bound
  concurrency at the pool size, and the stream must deliver per-token;
- balancer units (SyntheticBackends, no cluster): least-inflight must
  starve a slow replica that round-robin would keep feeding, and a
  backend-set swap must not drop in-flight requests;
- the rollout e2e (LocalCluster): a RollingUpdate of the serving
  Deployment mid-traffic with a PDB floor — zero failed requests and
  the Ready floor held is the zero-downtime verdict.
"""

import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.proxy import LeastInflightBalancer
from kubernetes1_tpu.workloads.loadgen import LoadGen
from kubernetes1_tpu.workloads.servefleet import (
    ServeFleet,
    SyntheticBackend,
    rolling_update,
    synthetic_factory,
)

APP = "llama-serve"


# ------------------------------------------------- batching (jax) ----


class TestContinuousBatching:
    @pytest.fixture(scope="class")
    def servers(self):
        from kubernetes1_tpu.workloads import llama

        cfg = llama.tiny()
        batched = llama.DecodeServer(cfg=cfg, seed=7, batching=True, slots=4)
        sequential = llama.DecodeServer(cfg=cfg, seed=7, batching=False)
        batched.warmup()
        sequential.warmup()
        yield batched, sequential
        batched.stop()
        sequential.stop()

    def test_batched_matches_sequential(self, servers):
        batched, sequential = servers
        for prompt in ([1, 2, 3], [9, 8], [42]):
            assert batched.generate(list(prompt), max_new=4) == \
                sequential.generate(list(prompt), max_new=4)

    def test_concurrent_requests_match_sequential(self, servers):
        batched, sequential = servers
        prompts = [[i + 1, i + 2] for i in range(6)]  # 6 requests, 4 slots
        want = [sequential.generate(list(p), max_new=4) for p in prompts]
        got = [None] * len(prompts)

        def one(i):
            got[i] = batched.generate(list(prompts[i]), max_new=4)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(prompts))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert got == want

    def test_slot_admission_bounds_concurrency(self, servers):
        batched, _ = servers
        engine = batched.engine
        leases = [engine.submit([5, i], max_new=4) for i in range(7)]
        peak = 0
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with engine._cond:
                peak = max(peak, len(engine._active))
                pending = len(engine._pending) + len(engine._active)
            if pending == 0:
                break
            time.sleep(0.01)
        outs = [lease.result(timeout=60) for lease in leases]
        assert all(len(o) == 4 for o in outs)
        assert peak <= engine.slots

    def test_streaming_delivers_per_token(self, servers):
        batched, _ = servers
        lease = batched.generate_stream([3, 1], max_new=4)
        toks = list(lease.stream())
        assert len(toks) == 4
        assert toks == batched.generate([3, 1], max_new=4)

    def test_slot_gauges_rendered(self, servers):
        batched, _ = servers
        text = batched.metrics.render()
        assert "ktpu_llama_slots_total" in text
        assert "ktpu_llama_slots_used" in text


# ------------------------------------------- balancer distribution ----


def _fleet_of(delays):
    backends = [SyntheticBackend(token_delay_s=d, slots=8).start()
                for d in delays]
    return backends, [("127.0.0.1", b.port) for b in backends]


def _drive(bal, seconds=1.2, qps=120):
    lg = LoadGen(bal.url, qps=qps, arrival="constant", seed=5,
                 max_new=6, stream=True, max_inflight=32)
    lg.start()
    time.sleep(seconds)
    lg.stop(drain_s=5.0)
    return lg.summary()


class TestLeastInflightRouting:
    def test_least_inflight_starves_slow_replica(self):
        backends, addrs = _fleet_of([0.001, 0.001, 0.030])
        bal = LeastInflightBalancer(seed=1, policy="least_inflight")
        try:
            bal.set_backends(addrs)
            s = _drive(bal)
            assert s["failed"] == 0
            stats = bal.stats()["backends"]
            slow = stats[f"127.0.0.1:{backends[2].port}"]["requests"]
            fast = min(stats[f"127.0.0.1:{b.port}"]["requests"]
                       for b in backends[:2])
            # the slow replica holds requests in flight longer, so
            # least-inflight must send it a clear minority
            assert slow < fast / 2, (slow, fast)
        finally:
            bal.stop()
            for b in backends:
                b.stop()

    def test_round_robin_splits_evenly(self):
        backends, addrs = _fleet_of([0.001, 0.001, 0.030])
        bal = LeastInflightBalancer(seed=1, policy="round_robin")
        try:
            bal.set_backends(addrs)
            s = _drive(bal)
            assert s["failed"] == 0
            counts = [v["requests"]
                      for v in bal.stats()["backends"].values()]
            assert max(counts) - min(counts) <= 1, counts
        finally:
            bal.stop()
            for b in backends:
                b.stop()

    def test_backend_swap_keeps_inflight_alive(self):
        backends, addrs = _fleet_of([0.004, 0.004])
        bal = LeastInflightBalancer(seed=2)
        try:
            bal.set_backends(addrs)
            lg = LoadGen(bal.url, qps=80, arrival="constant", seed=6,
                         max_new=8, stream=True).start()
            time.sleep(0.5)
            # drop backend 0 from the set mid-traffic: it must drain
            # (finish its in-flight streams), not reset them
            bal.set_backends(addrs[1:])
            time.sleep(0.5)
            lg.stop(drain_s=5.0)
            s = lg.summary()
            assert s["failed"] == 0, s
            assert s["acked"] > 20
            live = bal.stats()["backends"]
            assert list(live) == [f"127.0.0.1:{backends[1].port}"]
        finally:
            bal.stop()
            for b in backends:
                b.stop()

    def test_dead_backend_retries_to_survivor(self):
        backends, addrs = _fleet_of([0.002])
        dead = ("127.0.0.1", 1)  # nothing listens there
        bal = LeastInflightBalancer(seed=3)
        try:
            bal.set_backends([dead] + addrs)
            s = _drive(bal, seconds=0.5, qps=60)
            assert s["failed"] == 0, s
            assert s["acked"] > 10
            assert bal.stats()["retries"] > 0
        finally:
            bal.stop()
            for b in backends:
                b.stop()


# ----------------------------------------------- rollout e2e ----------


class TestRolloutUnderTraffic:
    def test_rolling_update_zero_failed_requests(self):
        from kubernetes1_tpu.client import InformerFactory
        from kubernetes1_tpu.localcluster import LocalCluster
        from kubernetes1_tpu.proxy import EndpointsBalancerSync

        cluster = LocalCluster(nodes=2, tpus_per_node=4).start()
        cs = cluster.cs
        factory = InformerFactory(cs)
        fleet = bal = lg = None
        try:
            dep = t.Deployment()
            dep.metadata.name = APP
            dep.spec.replicas = 3
            dep.spec.selector = t.LabelSelector(match_labels={"app": APP})
            dep.spec.template.metadata.labels = {"app": APP}
            c = t.Container(name="serve", image="llama-serve",
                            command=["serve"])
            c.resources.requests = {"cpu": "10m"}
            dep.spec.template.spec.containers = [c]
            cs.deployments.create(dep)

            svc = t.Service()
            svc.metadata.name = APP
            svc.spec.selector = {"app": APP}
            svc.spec.ports = [t.ServicePort(port=80)]
            cs.services.create(svc, "default")

            pdb = t.PodDisruptionBudget()
            pdb.metadata.name = f"{APP}-pdb"
            pdb.spec.selector = t.LabelSelector(match_labels={"app": APP})
            pdb.spec.min_available = 2
            cs.poddisruptionbudgets.create(pdb, "default")

            fleet = ServeFleet(cs, factory, APP,
                               backend_factory=synthetic_factory(
                                   token_delay_s=0.002, slots=8))
            bal = LeastInflightBalancer(seed=0)
            EndpointsBalancerSync(bal, factory, "default", APP,
                                  resolver=fleet.resolver)
            factory.start_all()
            factory.wait_for_sync()
            assert fleet.wait_backends(3, timeout=30) == 3
            deadline = time.monotonic() + 15
            while (time.monotonic() < deadline
                   and len(bal.stats()["backends"]) < 3):
                time.sleep(0.05)
            assert len(bal.stats()["backends"]) == 3

            lg = LoadGen(bal.url, qps=30, stream=True, seed=1).start()
            time.sleep(1.0)
            ru = rolling_update(cs, APP, timeout=90.0)
            time.sleep(1.0)
            lg.stop(drain_s=5.0)
            s = lg.summary()
            assert ru["completed"], ru
            assert s["failed"] == 0, s
            assert s["acked"] > 20, s
            # the PDB floor (minAvailable=2 of 3) must hold throughout:
            # the rolling logic may never take two replicas down at once
            assert ru["min_ready_observed"] >= 2, ru
        finally:
            if lg is not None:
                lg.stop(drain_s=0.5)
            if bal is not None:
                bal.stop()
            if fleet is not None:
                fleet.stop()
            cluster.stop()
