"""Cluster DNS: service discovery by stable name (ref: kube-dns addon +
kubelet --cluster-dns; dns/server.py docstring for the node-local shape)."""

import os
import socket

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver.server import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.dns import ClusterDNS, encode_query, parse_response
from kubernetes1_tpu.utils.waitutil import must_poll_until


def make_service(name, ns="default", cluster_ip="", selector=None):
    svc = t.Service()
    svc.metadata.name = name
    svc.metadata.namespace = ns
    svc.spec.cluster_ip = cluster_ip
    svc.spec.selector = selector or {"app": name}
    svc.spec.ports = [t.ServicePort(port=80)]
    return svc


@pytest.fixture()
def dns_env():
    master = Master().start()
    cs = Clientset(master.url)
    dns = ClusterDNS(cs, bind_ip="127.0.0.1", port=0).start()
    yield {"cs": cs, "dns": dns}
    dns.stop()
    cs.close()
    master.stop()


def query(dns, name, timeout=5.0):
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    s.sendto(encode_query(name), (dns.ip, dns.port))
    data, _ = s.recvfrom(4096)
    s.close()
    return parse_response(data)


class TestResolution:
    def test_service_a_record_all_name_forms(self, dns_env):
        cs, dns = dns_env["cs"], dns_env["dns"]
        created = cs.services.create(make_service("redis-master"))
        ip = created.spec.cluster_ip
        assert ip.startswith("10.96.")
        must_poll_until(lambda: dns.resolve("redis-master.default") == [ip],
                        timeout=10.0, desc="informer sees the service")
        for form in ("redis-master.default",
                     "redis-master.default.svc",
                     "redis-master.default.svc.cluster.local",
                     "redis-master.default.svc.cluster.local."):
            rcode, ips = query(dns, form)
            assert (rcode, ips) == (0, [ip]), form

    def test_unknown_service_nxdomain(self, dns_env):
        rcode, ips = query(dns_env["dns"], "nope.default.svc.cluster.local")
        assert rcode == 3 and ips == []

    def test_headless_service_returns_endpoints(self, dns_env):
        cs, dns = dns_env["cs"], dns_env["dns"]
        cs.services.create(make_service("gang", cluster_ip="None"))
        ep = t.Endpoints()
        ep.metadata.name = "gang"
        ep.subsets = [t.EndpointSubset(addresses=[
            t.EndpointAddress(ip="10.0.0.1"), t.EndpointAddress(ip="10.0.0.2"),
        ])]
        cs.endpoints.create(ep)
        must_poll_until(
            lambda: sorted(dns.resolve("gang.default") or []) ==
            ["10.0.0.1", "10.0.0.2"],
            timeout=10.0, desc="headless endpoints resolve")
        rcode, ips = query(dns, "gang.default.svc.cluster.local")
        assert rcode == 0 and sorted(ips) == ["10.0.0.1", "10.0.0.2"]

    def test_service_created_after_watcher_resolves(self, dns_env):
        """THE r3 gap: *_SERVICE_HOST env is snapshot-at-start; DNS answers
        live — a service created later must become resolvable."""
        cs, dns = dns_env["cs"], dns_env["dns"]
        rcode, _ = query(dns, "late.default.svc.cluster.local")
        assert rcode == 3  # not there yet
        created = cs.services.create(make_service("late"))
        must_poll_until(
            lambda: query(dns, "late.default.svc.cluster.local")
            == (0, [created.spec.cluster_ip]),
            timeout=10.0, desc="late-created service resolves")

    def test_non_cluster_name_not_ours(self, dns_env):
        # upstream-less server answers SERVFAIL rather than lying NXDOMAIN
        dns = dns_env["dns"]
        dns._upstream = ""
        rcode, ips = query(dns, "example.com")
        assert rcode == 2 and ips == []

    def test_aaaa_for_existing_name_empty_noerror(self, dns_env):
        cs, dns = dns_env["cs"], dns_env["dns"]
        created = cs.services.create(make_service("v6less"))
        must_poll_until(lambda: dns.resolve("v6less.default"), timeout=10.0,
                        desc="service visible")
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(5.0)
        s.sendto(encode_query("v6less.default.svc.cluster.local", qtype=28),
                 (dns.ip, dns.port))
        rcode, ips = parse_response(s.recvfrom(4096)[0])
        s.close()
        assert rcode == 0 and ips == []  # exists, no AAAA records

    def test_resolv_conf_shape(self, dns_env):
        rc = dns_env["dns"].resolv_conf("team-a")
        assert f"nameserver {dns_env['dns'].ip}" in rc
        assert "search team-a.svc.cluster.local svc.cluster.local" in rc

    def test_forward_concurrency_bounded(self, dns_env):
        """A pod spamming external lookups must not exhaust threads in the
        kubelet process hosting the resolver: beyond the semaphore bound
        the server answers SERVFAIL instead of spawning another forward
        thread (ADVICE r4 medium)."""
        import threading

        from kubernetes1_tpu.dns.server import _build_response

        dns = dns_env["dns"]
        slow = threading.Event()

        def stuck_forward(query, qid, question):
            slow.wait(2.0)  # models an unresponsive upstream
            return _build_response(qid, question, 2, [])

        dns._forward = stuck_forward
        before = threading.active_count()
        # saturate all 16 slots, then some: the excess must come back
        # SERVFAIL immediately rather than waiting out the 2s timeout
        got_servfail = 0
        socks = []
        for i in range(40):  # rapid-fire so slots can't free up in between
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.settimeout(0.5)
            s.sendto(encode_query(f"x{i}.example.com"), (dns.ip, dns.port))
            socks.append(s)
        for s in socks:
            try:
                rcode, _ = parse_response(s.recvfrom(4096)[0])
                if rcode == 2:
                    got_servfail += 1
            except socket.timeout:
                pass  # slot held: answer comes only when the upstream does
            s.close()
        # 40 queries minus 16 slots: the rest SERVFAIL immediately
        assert got_servfail >= 10
        # thread growth bounded by the slot count, not the query count
        assert threading.active_count() - before <= 17
        slow.set()  # release the stuck forwards before teardown


@pytest.mark.skipif(os.geteuid() != 0, reason="port 53 + mount ns need root")
class TestPodResolution:
    def test_pod_resolves_service_by_bare_name(self, tmp_path):
        """guestbook shape: the frontend reaches redis-master by NAME, via
        the bind-mounted resolv.conf + search path — including a service
        created AFTER the pod started."""
        from kubernetes1_tpu.kubelet import Kubelet, ProcessRuntime

        master = Master().start()
        cs = Clientset(master.url)
        runtime = ProcessRuntime(root_dir=str(tmp_path / "ktpu"))
        if not runtime._mount_ns:
            master.stop()
            pytest.skip("host cannot create mount namespaces")
        kubelet = Kubelet(cs, node_name="dns-node", runtime=runtime,
                          plugin_dir=str(tmp_path / "plugins"),
                          heartbeat_interval=0.5, sync_interval=0.3,
                          pleg_interval=0.3)
        if kubelet.cluster_dns is None:
            kubelet.stop = lambda: None
            master.stop()
            pytest.skip("cluster DNS bind unavailable")
        kubelet.start()
        try:
            pod = t.Pod()
            pod.metadata.name = "frontend"
            pod.spec.node_name = "dns-node"
            pod.spec.restart_policy = "Never"
            # the service does NOT exist when the pod starts; the pod polls
            # until the name resolves (closing the env-snapshot gap)
            pod.spec.containers = [t.Container(
                name="c", image="img",
                command=["sh", "-c",
                         "for i in $(seq 1 60); do "
                         "getent hosts redis-master && exit 0; sleep 0.5; "
                         "done; exit 1"])]
            cs.pods.create(pod)
            must_poll_until(
                lambda: cs.pods.get("frontend", "default").status.phase
                == "Running", timeout=30.0, desc="frontend running")
            created = cs.services.create(make_service("redis-master"))
            must_poll_until(
                lambda: cs.pods.get("frontend", "default").status.phase
                == "Succeeded", timeout=45.0,
                desc="frontend resolved redis-master by bare name")
            cid = next(c.id for c in runtime.list_containers()
                       if c.state == "EXITED")
            assert created.spec.cluster_ip in runtime.read_log(cid)
        finally:
            kubelet.stop()
            runtime.kill_all()  # containers must not outlive the test
            cs.close()
            master.stop()
