"""Custom-metrics plane: pod /metrics scraping, the custom-metrics API,
and metric-driven autoscaling.

Covers the PR's acceptance surface:
- obs/appmetrics: the workload registry (text format, sliding-window
  rate gauges) and the scrape annotation contract;
- kubelet/podscrape: annotated pods scraped on per-pod threads —
  publishes PodCustomMetrics with the pod's labels + scrape-derived
  counter rates, marks LAST-GOOD samples stale on endpoint death
  (never silently fresh), a wedged pod endpoint stalls only its own
  thread, vanished pods' objects are GC'd;
- the apiserver's aggregated custom-metrics read path (the
  custom.metrics.k8s.io GET shape): star/single-pod queries, label
  selection, stale forwarding;
- the HPA's v2 evaluation: tolerance band, min/max clamping, Pods-type
  target-average-value metrics, max-of-metrics, stabilization windows,
  missing/stale-metrics-skips-cycle — and the v1 CPU shorthand
  consuming PodMetrics from an informer snapshot (no live GET per pod
  per cycle);
- the LocalCluster e2e: an HPA scales a Deployment out AND back driven
  ONLY by a custom QPS metric scraped from pod /metrics, reaction time
  reported.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, InformerFactory
from kubernetes1_tpu.controllers import podautoscaler as hpa_mod
from kubernetes1_tpu.controllers.podautoscaler import (
    HorizontalPodAutoscalerController,
)
from kubernetes1_tpu.kubelet.podscrape import PodScraper
from kubernetes1_tpu.localcluster import LocalCluster
from kubernetes1_tpu.obs.appmetrics import (
    AppMetrics,
    sample_value,
    scrape_annotations,
    scrape_target,
)
from kubernetes1_tpu.utils.waitutil import must_poll_until


def fetch(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def simple_pod(name, node="n1", labels=None, annotations=None,
               ns="default"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    pod.metadata.labels = labels or {}
    if annotations:
        pod.metadata.annotations = annotations
    pod.spec.containers = [t.Container(name="c", image="busybox")]
    pod.spec.node_name = node
    return pod


# ----------------------------------------------------------- appmetrics


class TestAppMetrics:
    def test_text_format_and_rate_gauge(self):
        am = AppMetrics(rate_window_s=2.0)
        am.counter("ktpu_x_requests_total").inc(3)
        am.gauge("ktpu_x_inflight").set(2)
        am.histogram("ktpu_x_latency_seconds").observe(0.01)
        am.mark("ktpu_x_qps", 4)
        text = am.render()
        assert "# TYPE ktpu_x_requests_total counter" in text
        assert "ktpu_x_requests_total 3.0" in text
        assert "ktpu_x_latency_seconds_bucket" in text
        # 4 events over a 2s window = 2/s
        assert "ktpu_x_qps 2.0" in text

    def test_served_endpoint(self):
        am = AppMetrics().serve()
        try:
            am.gauge("ktpu_x_g").set(7.5)
            assert "ktpu_x_g 7.5" in fetch(am.url + "/metrics")
        finally:
            am.stop()

    def test_scrape_annotation_contract(self):
        pod = simple_pod("p", annotations=scrape_annotations(
            8080, path="/m", host="127.0.0.1"))
        assert scrape_target(pod) == "http://127.0.0.1:8080/m"
        # default host falls back to the pod IP
        pod2 = simple_pod("p2", annotations=scrape_annotations(8080))
        pod2.status.pod_ip = "10.0.0.9"
        assert scrape_target(pod2) == "http://10.0.0.9:8080/metrics"
        # not annotated / malformed = opted out, never a crash
        assert scrape_target(simple_pod("p3")) is None
        bad = simple_pod("p4", annotations={
            "obs.ktpu.io/scrape-port": "not-a-port"})
        assert scrape_target(bad) is None

    def test_sample_value_fold(self):
        pcm = t.PodCustomMetrics(samples=[
            t.MetricSample(name="ktpu_q", value=5.0),
            t.MetricSample(name="ktpu_l", value=1.0, labels={"a": "x"}),
            t.MetricSample(name="ktpu_l", value=2.0, labels={"a": "y"}),
        ])
        assert sample_value(pcm, "ktpu_q") == 5.0
        assert sample_value(pcm, "ktpu_l") == 3.0  # labeled children sum
        assert sample_value(pcm, "ktpu_missing") is None


# ---------------------------------------------------------- pod scraper


@pytest.fixture()
def master():
    m = Master(port=0).start()
    cs = Clientset(m.url)
    yield m, cs
    cs.close()
    m.stop()


class TestPodScraper:
    def _scraped_pod(self, cs, am, name="p1", labels=None):
        pod = simple_pod(name, labels=labels or {"app": "x"},
                         annotations=scrape_annotations(
                             am.port, host="127.0.0.1"))
        cs.pods.create(pod)
        pods, _ = cs.pods.list()
        return pods

    def test_publishes_samples_labels_and_rates(self, master):
        _m, cs = master
        am = AppMetrics().serve()
        am.gauge("ktpu_t_qps").set(42.0)
        am.counter("ktpu_t_requests_total").inc(10)
        ps = PodScraper(cs, "n1", interval=0.1)
        try:
            ps.reconcile(self._scraped_pod(cs, am))
            must_poll_until(
                lambda: _pcm_or_none(cs, "p1") is not None,
                timeout=10.0, desc="PodCustomMetrics published")
            pcm = cs.podcustommetrics.get("p1", "default")
            assert pcm.stale is False
            assert pcm.metadata.labels == {"app": "x"}  # pod labels copied
            assert sample_value(pcm, "ktpu_t_qps") == 42.0
            assert sample_value(pcm, "ktpu_t_requests_total") == 10.0
            # counter rate derived between scrapes: bump and watch
            am.counter("ktpu_t_requests_total").inc(100)

            def rate_seen():
                pcm = _pcm_or_none(cs, "p1")
                v = pcm and sample_value(
                    pcm, "ktpu_t_requests_total:rate")
                return v is not None and v > 0
            must_poll_until(rate_seen, timeout=10.0, desc="derived rate")
        finally:
            ps.stop()
            am.stop()

    def test_endpoint_death_marks_stale_keeps_last_good(self, master):
        _m, cs = master
        am = AppMetrics().serve()
        am.gauge("ktpu_t_qps").set(9.0)
        ps = PodScraper(cs, "n1", interval=0.1)
        try:
            ps.reconcile(self._scraped_pod(cs, am))
            must_poll_until(
                lambda: (_pcm_or_none(cs, "p1") or t.PodCustomMetrics(
                    stale=True)).stale is False,
                timeout=10.0, desc="fresh publish")
            am.stop()  # the workload dies
            must_poll_until(
                lambda: (_pcm_or_none(cs, "p1")
                         or t.PodCustomMetrics()).stale,
                timeout=10.0, desc="stale marked")
            pcm = cs.podcustommetrics.get("p1", "default")
            # last-good samples survive the death, marked stale
            assert sample_value(pcm, "ktpu_t_qps") == 9.0
            text = ps.render_metrics()
            assert 'ktpu_podscrape_up{pod="default/p1"} 0' in text
        finally:
            ps.stop()

    def test_restart_adopts_and_stale_marks_preexisting_object(
            self, master):
        """Kubelet restart mid-outage: a NEW scraper (no in-memory
        last-good) must find the pre-restart PodCustomMetrics still
        claiming stale=False and mark it stale with its samples held —
        else consumers read a dead endpoint's last samples as live
        truth for the whole outage."""
        _m, cs = master
        am = AppMetrics().serve()
        am.gauge("ktpu_t_qps").set(7.0)
        ps = PodScraper(cs, "n1", interval=0.1)
        try:
            pods = self._scraped_pod(cs, am)
            ps.reconcile(pods)
            must_poll_until(
                lambda: (_pcm_or_none(cs, "p1") or t.PodCustomMetrics(
                    stale=True)).stale is False,
                timeout=10.0, desc="fresh publish")
            ps.stop()   # the kubelet dies...
            am.stop()   # ...and so does the workload endpoint
            assert cs.podcustommetrics.get("p1", "default").stale is False
            ps2 = PodScraper(cs, "n1", interval=0.1)  # restarted kubelet
            try:
                ps2.reconcile(pods)
                must_poll_until(
                    lambda: (_pcm_or_none(cs, "p1")
                             or t.PodCustomMetrics()).stale,
                    timeout=10.0, desc="adopted object stale-marked")
                # the pre-restart last-good samples survive the adoption
                pcm = cs.podcustommetrics.get("p1", "default")
                assert sample_value(pcm, "ktpu_t_qps") == 7.0
            finally:
                ps2.stop()
        finally:
            ps.stop()
            am.stop()

    def test_dead_endpoint_stalls_only_its_own_thread(self, master):
        """The faultline-invariant shape, node-local: pod A's endpoint
        is a black hole (accepts, never answers); pod B's samples keep
        flowing and reconcile never blocks."""
        _m, cs = master
        # black hole server: accepts connections, never responds
        import socket as _socket

        hole = _socket.socket()
        hole.bind(("127.0.0.1", 0))
        hole.listen(8)
        hole_port = hole.getsockname()[1]
        am = AppMetrics().serve()
        am.gauge("ktpu_t_qps").set(5.0)
        pod_a = simple_pod("hole", annotations={
            "obs.ktpu.io/scrape-port": str(hole_port),
            "obs.ktpu.io/scrape-host": "127.0.0.1"})
        pod_b = simple_pod("live", annotations=scrape_annotations(
            am.port, host="127.0.0.1"))
        cs.pods.create(pod_a)
        cs.pods.create(pod_b)
        pods, _ = cs.pods.list()
        ps = PodScraper(cs, "n1", interval=0.1, fetch_timeout=1.0)
        try:
            t0 = time.monotonic()
            ps.reconcile(pods)
            assert time.monotonic() - t0 < 0.5  # reconcile never scrapes
            must_poll_until(
                lambda: _pcm_or_none(cs, "live") is not None,
                timeout=10.0, desc="live pod published")
            # the live pod's samples keep updating while the hole wedges
            am.gauge("ktpu_t_qps").set(6.0)
            must_poll_until(
                lambda: sample_value(_pcm_or_none(cs, "live"),
                                     "ktpu_t_qps") == 6.0,
                timeout=10.0, desc="live pod stays fresh")
            assert _pcm_or_none(cs, "hole") is None  # never answered
        finally:
            ps.stop()
            am.stop()
            hole.close()

    def test_vanished_pod_object_gcd(self, master):
        _m, cs = master
        am = AppMetrics().serve()
        am.gauge("ktpu_t_qps").set(1.0)
        ps = PodScraper(cs, "n1", interval=0.1)
        try:
            ps.reconcile(self._scraped_pod(cs, am))
            must_poll_until(
                lambda: _pcm_or_none(cs, "p1") is not None,
                timeout=10.0, desc="published")
            ps.reconcile([])  # pod gone
            must_poll_until(
                lambda: _pcm_or_none(cs, "p1") is None,
                timeout=10.0, desc="object GC'd")
        finally:
            ps.stop()
            am.stop()

    def test_unannotated_pods_cost_nothing(self, master):
        _m, cs = master
        ps = PodScraper(cs, "n1", interval=0.1)
        try:
            cs.pods.create(simple_pod("plain"))
            pods, _ = cs.pods.list()
            ps.reconcile(pods)
            assert ps.targets() == []
        finally:
            ps.stop()


def _pcm_or_none(cs, name, ns="default"):
    try:
        return cs.podcustommetrics.get(name, ns)
    except Exception:  # noqa: BLE001 — NotFound/settling
        return None


# ------------------------------------------------- custom-metrics API


class TestCustomMetricsAPI:
    def _seed(self, cs):
        for i, (app, stale) in enumerate(
                [("a", False), ("a", False), ("b", True)]):
            pcm = t.PodCustomMetrics(
                timestamp="ts", stale=stale,
                samples=[t.MetricSample(name="ktpu_q", value=float(i + 1))])
            pcm.metadata.name = f"p{i}"
            pcm.metadata.labels = {"app": app}
            cs.podcustommetrics.create(pcm, "default")

    def test_star_query_and_label_selection(self, master):
        m, cs = master
        self._seed(cs)
        base = (m.url + "/apis/custom.metrics.k8s.io/v1"
                "/namespaces/default/pods")
        data = json.loads(fetch(f"{base}/*/ktpu_q"))
        assert data["kind"] == "MetricValueList"
        rows = {(i["describedObject"]["name"], i["value"], i["stale"])
                for i in data["items"]}
        assert rows == {("p0", 1.0, False), ("p1", 2.0, False),
                        ("p2", 3.0, True)}  # stale forwarded, not dropped
        sel = json.loads(fetch(f"{base}/*/ktpu_q?labelSelector=app%3Da"))
        assert {i["describedObject"]["name"] for i in sel["items"]} \
            == {"p0", "p1"}

    def test_single_pod_and_missing_404(self, master):
        m, cs = master
        self._seed(cs)
        base = (m.url + "/apis/custom.metrics.k8s.io/v1"
                "/namespaces/default/pods")
        one = json.loads(fetch(f"{base}/p1/ktpu_q"))
        assert [i["value"] for i in one["items"]] == [2.0]
        with pytest.raises(urllib.error.HTTPError) as ei:
            fetch(f"{base}/p1/ktpu_nope")
        assert ei.value.code == 404


# ------------------------------------------------------------ HPA units


@pytest.fixture()
def hpa_rig():
    """Master + a synchronously-driven HPA controller: informers run,
    workers don't — tests call _reconcile directly for deterministic
    cycles."""
    m = Master(port=0).start()
    cs = Clientset(m.url)
    factory = InformerFactory(cs)
    ctrl = HorizontalPodAutoscalerController(cs, factory)
    ctrl.setup()
    factory.start_all()
    factory.wait_for_sync()
    yield m, cs, ctrl
    factory.stop_all()
    cs.close()
    m.stop()


def make_rs(cs, name="workers", replicas=2, app="w"):
    rs = t.ReplicaSet()
    rs.metadata.name = name
    rs.spec.replicas = replicas
    rs.spec.selector = t.LabelSelector(match_labels={"app": app})
    rs.spec.template.metadata.labels = {"app": app}
    rs.spec.template.spec.containers = [
        t.Container(name="c", image="busybox",
                    resources=t.ResourceRequirements(
                        requests={"cpu": "100m"}))]
    return cs.replicasets.create(rs)


def make_running_pod(cs, name, app="w", cpu="100m"):
    pod = simple_pod(name, labels={"app": app})
    pod.spec.containers[0].resources = t.ResourceRequirements(
        requests={"cpu": cpu})
    created = cs.pods.create(pod)
    created.status.phase = t.POD_RUNNING
    return cs.pods.update_status(created)


def put_pcm(cs, pod_name, qps, stale=False, metric="ktpu_q"):
    cur = _pcm_or_none(cs, pod_name)
    pcm = t.PodCustomMetrics(
        timestamp="ts", stale=stale,
        samples=[t.MetricSample(name=metric, value=float(qps))])
    pcm.metadata.name = pod_name
    pcm.metadata.namespace = "default"
    if cur is not None:
        pcm.metadata.resource_version = cur.metadata.resource_version
        return cs.podcustommetrics.update(pcm)
    return cs.podcustommetrics.create(pcm, "default")


def pods_hpa(name="workers-hpa", target=10.0, min_r=1, max_r=5,
             metric="ktpu_q", kind="ReplicaSet", tname="workers"):
    hpa = t.HorizontalPodAutoscaler()
    hpa.metadata.name = name
    hpa.spec.scale_target_ref = t.CrossVersionObjectReference(
        kind=kind, name=tname)
    hpa.spec.min_replicas = min_r
    hpa.spec.max_replicas = max_r
    hpa.spec.metrics = [t.MetricSpec(type="Pods", pods=t.PodsMetricSource(
        metric_name=metric, target_average_value=target))]
    return hpa


def _wait_informers(ctrl, cs, pods=(), pcms=(), hpas=()):
    must_poll_until(
        lambda: all(ctrl.pods.get(f"default/{p}") is not None
                    for p in pods)
        and all((ctrl.podcustommetrics.get(f"default/{p}") or
                 t.PodCustomMetrics()).metadata.name == p for p in pcms)
        and all(ctrl.hpas.get(f"default/{h}") is not None for h in hpas),
        timeout=10.0, desc="informers caught up")


class TestHPAEvaluation:
    def _prep(self, cs, ctrl, replicas=2, qps=(), hpa=None):
        make_rs(cs, replicas=replicas)
        for i, q in enumerate(qps):
            make_running_pod(cs, f"w{i}")
            put_pcm(cs, f"w{i}", q)
        hpa = hpa or pods_hpa()
        created = cs.horizontalpodautoscalers.create(hpa)
        _wait_informers(
            ctrl, cs, pods=[f"w{i}" for i in range(len(qps))],
            pcms=[f"w{i}" for i in range(len(qps))],
            hpas=[hpa.metadata.name])
        return created

    def _sync_pcm(self, ctrl, name, stale=None, value=None,
                  metric="ktpu_q"):
        def caught_up():
            pcm = ctrl.podcustommetrics.get(f"default/{name}")
            if pcm is None:
                return False
            if stale is not None and pcm.stale != stale:
                return False
            if value is not None \
                    and sample_value(pcm, metric) != value:
                return False
            return True
        must_poll_until(caught_up, timeout=10.0, desc="pcm informer")

    def test_tolerance_band_holds(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = self._prep(cs, ctrl, replicas=2, qps=(10.5, 10.5))
        ctrl._reconcile(hpa)
        assert cs.replicasets.get("workers").spec.replicas == 2  # ±10%

    def test_scale_out_and_clamp_to_max(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = self._prep(cs, ctrl, replicas=2, qps=(100.0, 100.0))
        ctrl._reconcile(hpa)
        # ceil(2 * 100/10) = 20, clamped to max 5
        assert cs.replicasets.get("workers").spec.replicas == 5

    def test_scale_down_and_clamp_to_min(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = self._prep(cs, ctrl, replicas=2, qps=(0.1, 0.1),
                         hpa=pods_hpa(min_r=2))
        ctrl._reconcile(hpa)
        assert cs.replicasets.get("workers").spec.replicas == 2  # min clamp

    def test_missing_metrics_skip_cycle(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = self._prep(cs, ctrl, replicas=3, qps=())
        make_running_pod(cs, "w0")  # a pod with NO PodCustomMetrics
        _wait_informers(ctrl, cs, pods=["w0"])
        before = hpa_mod.hpa_missing_metric_cycles_total.value
        ctrl._reconcile(hpa)
        assert cs.replicasets.get("workers").spec.replicas == 3  # held
        assert hpa_mod.hpa_missing_metric_cycles_total.value == before + 1

    def test_stale_metrics_count_as_missing(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = self._prep(cs, ctrl, replicas=3, qps=(100.0,))
        put_pcm(cs, "w0", 100.0, stale=True)
        self._sync_pcm(ctrl, "w0", stale=True)
        ctrl._reconcile(hpa)
        # the only sample is stale -> no usable signal -> hold
        assert cs.replicasets.get("workers").spec.replicas == 3

    def test_partial_outage_blocks_scale_down(self, hpa_rig):
        """One metric readable and idle, the other missing: scale-UP on
        the readable subset is safe (max-of-metrics — a missing vote
        could only raise desired), but a scale-DOWN must hold — the
        missing metric might be the saturated one."""
        _m, cs, ctrl = hpa_rig
        make_rs(cs, replicas=4)
        make_running_pod(cs, "w0")
        pcm = t.PodCustomMetrics(timestamp="ts", samples=[
            t.MetricSample(name="ktpu_a", value=0.5)])  # idle
        pcm.metadata.name = "w0"
        cs.podcustommetrics.create(pcm, "default")
        hpa = pods_hpa(max_r=10)
        hpa.spec.metrics = [
            t.MetricSpec(type="Pods", pods=t.PodsMetricSource(
                metric_name="ktpu_a", target_average_value=10.0)),
            t.MetricSpec(type="Pods", pods=t.PodsMetricSource(
                metric_name="ktpu_missing", target_average_value=10.0)),
        ]
        created = cs.horizontalpodautoscalers.create(hpa)
        _wait_informers(ctrl, cs, pods=["w0"], pcms=["w0"],
                        hpas=["workers-hpa"])
        before = hpa_mod.hpa_missing_metric_cycles_total.value
        ctrl._reconcile(created)
        # ktpu_a alone says drain to min — held instead, and counted
        assert cs.replicasets.get("workers").spec.replicas == 4
        assert hpa_mod.hpa_missing_metric_cycles_total.value == before + 1

    def test_multi_metric_max_wins(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        make_rs(cs, replicas=2)
        make_running_pod(cs, "w0")
        # two Pods metrics: one on target (no change), one 3x over
        pcm = t.PodCustomMetrics(timestamp="ts", samples=[
            t.MetricSample(name="ktpu_a", value=10.0),
            t.MetricSample(name="ktpu_b", value=30.0)])
        pcm.metadata.name = "w0"
        cs.podcustommetrics.create(pcm, "default")
        hpa = pods_hpa(max_r=10)
        hpa.spec.metrics = [
            t.MetricSpec(type="Pods", pods=t.PodsMetricSource(
                metric_name="ktpu_a", target_average_value=10.0)),
            t.MetricSpec(type="Pods", pods=t.PodsMetricSource(
                metric_name="ktpu_b", target_average_value=10.0)),
        ]
        created = cs.horizontalpodautoscalers.create(hpa)
        _wait_informers(ctrl, cs, pods=["w0"], pcms=["w0"],
                        hpas=["workers-hpa"])
        ctrl._reconcile(created)
        # ktpu_a says stay at 2, ktpu_b says ceil(2*3)=6 -> max wins
        assert cs.replicasets.get("workers").spec.replicas == 6

    def test_cpu_shorthand_uses_informer_snapshot(self, hpa_rig):
        """The v1 CPU path consumes PodMetrics via the informer — and
        still scales exactly as before."""
        _m, cs, ctrl = hpa_rig
        make_rs(cs, replicas=1)
        make_running_pod(cs, "w0", cpu="100m")
        pm = t.PodMetrics(timestamp="ts", containers=[
            t.ContainerMetrics(name="c", usage={"cpu": "400m"})])
        pm.metadata.name = "w0"
        cs.podmetrics.create(pm, "default")
        hpa = t.HorizontalPodAutoscaler()
        hpa.metadata.name = "cpu-hpa"
        hpa.spec.scale_target_ref = t.CrossVersionObjectReference(
            kind="ReplicaSet", name="workers")
        hpa.spec.min_replicas = 1
        hpa.spec.max_replicas = 4
        hpa.spec.target_cpu_utilization_percentage = 100
        created = cs.horizontalpodautoscalers.create(hpa)
        _wait_informers(ctrl, cs, pods=["w0"], hpas=["cpu-hpa"])
        must_poll_until(
            lambda: ctrl.podmetrics.get("default/w0") is not None,
            timeout=10.0, desc="podmetrics informer")
        ctrl._reconcile(created)
        # 400% of request vs 100% target -> ceil(1*4) = 4
        assert cs.replicasets.get("workers").spec.replicas == 4
        st = cs.horizontalpodautoscalers.get("cpu-hpa").status
        assert st.current_cpu_utilization_percentage == 400
        assert st.current_metric_values == {}  # v1 status shape untouched

    def test_scale_down_stabilization_window(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = pods_hpa()
        hpa.spec.scale_down_stabilization_seconds = 1.0
        # per-pod average exactly on target: the window seeds with a
        # stay-at-4 recommendation
        hpa = self._prep(cs, ctrl, replicas=4, qps=(10.0,), hpa=hpa)
        ctrl._reconcile(hpa)  # recommendation: stay at 4
        assert cs.replicasets.get("workers").spec.replicas == 4
        put_pcm(cs, "w0", 1.0)
        self._sync_pcm(ctrl, "w0", value=1.0)
        ctrl._reconcile(hpa)  # low, but the 4-rec is inside the window
        assert cs.replicasets.get("workers").spec.replicas == 4
        time.sleep(1.1)  # window passes
        ctrl._reconcile(hpa)
        assert cs.replicasets.get("workers").spec.replicas == 1

    def test_scale_up_stabilization_window(self, hpa_rig):
        _m, cs, ctrl = hpa_rig
        hpa = pods_hpa()
        hpa.spec.scale_up_stabilization_seconds = 1.0
        hpa = self._prep(cs, ctrl, replicas=1, qps=(10.0,), hpa=hpa)
        ctrl._reconcile(hpa)  # on target: window seeded with rec=1
        assert cs.replicasets.get("workers").spec.replicas == 1
        put_pcm(cs, "w0", 50.0)
        self._sync_pcm(ctrl, "w0", value=50.0)
        ctrl._reconcile(hpa)  # spike, but min-of-window is still 1
        assert cs.replicasets.get("workers").spec.replicas == 1
        time.sleep(1.1)
        ctrl._reconcile(hpa)  # the spike survived the window
        assert cs.replicasets.get("workers").spec.replicas == 5

    def test_rescale_emits_metrics_and_flightrec(self, hpa_rig):
        from kubernetes1_tpu.utils import flightrec

        _m, cs, ctrl = hpa_rig
        flightrec.reset()
        before = hpa_mod.rescales_snapshot()
        hpa = self._prep(cs, ctrl, replicas=1, qps=(100.0,))
        ctrl._reconcile(hpa)
        assert cs.replicasets.get("workers").spec.replicas == 5
        assert hpa_mod.rescales_snapshot() == before + 1
        ev = flightrec.last_event("hpa")
        assert ev is not None and ev["kind"] == flightrec.HPA_RESCALE
        assert ev["from_replicas"] == 1 and ev["to_replicas"] == 5
        assert hpa_mod.hpa_reaction_seconds.count >= 1

    def test_status_conflict_absorbed(self, hpa_rig):
        """The satellite: a conflicting concurrent status writer must
        not kill the cycle — the retry re-reads and lands the write."""
        _m, cs, ctrl = hpa_rig
        hpa = self._prep(cs, ctrl, replicas=2, qps=(10.0, 10.0))
        # racing writer: bump the HPA between the controller's get and
        # update by pre-bumping generation via a metadata update
        fresh = cs.horizontalpodautoscalers.get("workers-hpa")
        fresh.metadata.labels = {"race": "1"}
        cs.horizontalpodautoscalers.update(fresh)
        ctrl._reconcile(hpa)  # stale hpa object in hand: must still land
        st = cs.horizontalpodautoscalers.get("workers-hpa").status
        assert st.current_replicas == 2


# --------------------------------------------------------------- e2e


class TestAutoscaleE2E:
    def test_qps_scrape_drives_scale_out_and_back(self):
        """THE acceptance e2e: a Deployment scaled out AND back by an
        HPA whose only signal is a custom QPS metric scraped off pod
        /metrics, with the reaction time reported."""
        cluster = LocalCluster(nodes=1).start()
        am = AppMetrics()
        try:
            cluster.wait_ready(40)
            cs = cluster.cs
            qps = am.gauge("ktpu_e2e_qps")
            qps.set(10.0)
            am.serve()
            dep = t.Deployment()
            dep.metadata.name = "serve"
            dep.spec.replicas = 1
            dep.spec.selector = t.LabelSelector(
                match_labels={"app": "serve"})
            dep.spec.template.metadata.labels = {"app": "serve"}
            dep.spec.template.metadata.annotations = scrape_annotations(
                am.port, host="127.0.0.1")
            c = t.Container(name="c", image="busybox", command=["serve"])
            c.resources.requests = {"cpu": "10m"}
            dep.spec.template.spec.containers = [c]
            cs.deployments.create(dep)
            hpa = pods_hpa(name="serve-hpa", target=10.0, min_r=1,
                           max_r=3, metric="ktpu_e2e_qps",
                           kind="Deployment", tname="serve")
            cs.horizontalpodautoscalers.create(hpa)

            def replicas():
                return cs.deployments.get("serve").spec.replicas or 0

            must_poll_until(lambda: replicas() == 1, timeout=30.0,
                            desc="steady at 1 (qps on target)")
            reaction_count_before = hpa_mod.hpa_reaction_seconds.count
            qps.set(50.0)
            t0 = time.monotonic()
            must_poll_until(lambda: replicas() == 3, timeout=40.0,
                            desc="scale-out to max on 5x qps")
            out_reaction = time.monotonic() - t0
            qps.set(1.0)
            t1 = time.monotonic()
            must_poll_until(lambda: replicas() == 1, timeout=40.0,
                            desc="scale-back on idle qps")
            back_reaction = time.monotonic() - t1
            # reaction time reported: the SLI histogram observed the
            # out-of-band -> rescale-landed windows
            assert hpa_mod.hpa_reaction_seconds.count \
                > reaction_count_before
            print(f"\nscale-out reaction {out_reaction:.2f}s, "
                  f"scale-back {back_reaction:.2f}s, hpa-observed p99 "
                  f"{hpa_mod.hpa_reaction_seconds.quantile(0.99)}")
            # status carries the observed custom metric
            st = cs.horizontalpodautoscalers.get("serve-hpa").status
            assert "ktpu_e2e_qps" in st.current_metric_values
            # the fleet view shows the whole loop
            topo = json.loads(fetch(cluster.obs.url + "/debug/topology"))
            scaling = topo["scaling"]
            assert scaling["pod_scrape"]  # kubelet scrape health present
            assert "default/serve-hpa" in scaling["hpas"]
            fleet = fetch(cluster.obs.url + "/metrics")
            assert "ktpu_hpa_desired_replicas" in fleet
            assert "ktpu_podscrape_scrapes_total" in fleet
        finally:
            am.stop()
            cluster.stop()
