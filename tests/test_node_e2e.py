"""Node e2e (SURVEY §4 tier 3): real kubelet + real (process) runtime +
real device plugin + in-process apiserver/scheduler on one machine —
the reference's test/e2e_node pattern with everything statically linked
into the test process (services.go:61).

Covers the fork's signature e2e (gpu_device_plugin.go:36-120), TPU-style:
device assignment survives kubelet restart; a second pod gets different
chips; injected TPU_* env reaches the workload process.
"""

import os
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.deviceplugin.api import PluginServer, plugin_socket_path
from kubernetes1_tpu.deviceplugin.tpu_plugin import TPUDevicePlugin, _fake_devices
from kubernetes1_tpu.kubelet import FakeRuntime, Kubelet, ProcessRuntime
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod


@pytest.fixture()
def node_env(tmp_path):
    """master + scheduler + tpu plugin + kubelet with ProcessRuntime."""
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    plugin_dir = str(tmp_path / "plugins")
    impl = TPUDevicePlugin(devices=_fake_devices("v5e:4:s0:0"))
    plugin = PluginServer(impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
    plugin.start()
    runtime = ProcessRuntime(root_dir=str(tmp_path / "ktpu"))
    kubelet = Kubelet(
        cs,
        node_name="tpu-node-0",
        runtime=runtime,
        plugin_dir=plugin_dir,
        heartbeat_interval=0.5,
        sync_interval=0.3,
        pleg_interval=0.3,
    )
    kubelet.start()
    env = {
        "master": master, "cs": cs, "sched": sched, "plugin": plugin,
        "impl": impl, "runtime": runtime, "kubelet": kubelet,
        "plugin_dir": plugin_dir, "tmp": tmp_path,
    }
    yield env
    env["kubelet"].stop()
    runtime.kill_all()  # containers must not outlive the fixture
    sched.stop()
    plugin.stop()
    cs.close()
    master.stop()


def wait_phase(cs, name, phase, timeout=15.0, ns="default"):
    must_poll_until(
        lambda: cs.pods.get(name, ns).status.phase == phase,
        timeout=timeout,
        desc=f"pod {name} -> {phase}",
    )
    return cs.pods.get(name, ns)


def py_pod(name, code, tpus=0, restart="Never"):
    """Pod running a real python subprocess."""
    pod = make_tpu_pod(name, tpus=tpus)
    pod.spec.restart_policy = restart
    pod.spec.containers[0].command = [sys.executable, "-c", code]
    return pod


class TestNorthStarPath:
    def test_tpu_pod_runs_with_injected_env(self, node_env):
        """SURVEY §3.1: kubectl-create -> admission -> schedule -> bind ->
        kubelet admit -> InitContainer injection -> running process."""
        cs = node_env["cs"]
        tmp = node_env["tmp"]
        out = str(tmp / "envdump.txt")
        code = (
            "import os,json;"
            f"open({out!r},'w').write(json.dumps("
            "{k:v for k,v in os.environ.items() if k.startswith('TPU')}))"
        )
        pod = py_pod("mnist", code, tpus=2)
        cs.pods.create(pod)
        bound = wait_phase(cs, "mnist", t.POD_SUCCEEDED)
        assert bound.spec.node_name == "tpu-node-0"
        assigned = bound.spec.extended_resources[0].assigned
        assert len(assigned) == 2
        import json

        envs = json.loads(open(out).read())
        # visible chip indices correspond 1:1 to the assigned device IDs
        indices = envs["TPU_VISIBLE_CHIPS"].split(",")
        assert len(indices) == 2 and len(set(indices)) == 2
        assert sorted(indices) == sorted(i.rsplit("chip", 1)[1] for i in assigned)
        # NOTE: TPU_ACCELERATOR_TYPE/TPU_TOPOLOGY are asserted in the plugin
        # unit tests instead — this machine's TPU access hook (axon
        # sitecustomize) force-overwrites them in every child interpreter.
        assert envs["TPU_SLICE_ID"] == "s0"
        assert envs["TPU_HOST_INDEX"] == "0"
        assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"

    def test_node_advertises_device_inventory(self, node_env):
        cs = node_env["cs"]
        must_poll_until(
            lambda: len(
                (cs.nodes.get("tpu-node-0", "").status.extended_resources or {}).get(
                    "google.com/tpu", []
                )
            )
            == 4,
            desc="node advertises 4 chips",
        )
        node = cs.nodes.get("tpu-node-0", "")
        dev = node.status.extended_resources["google.com/tpu"][0]
        assert dev.attributes[t.ATTR_TPU_TYPE] == "v5e"

    def test_failing_container_restart_policy(self, node_env):
        cs = node_env["cs"]
        pod = py_pod("crasher", "import sys; sys.exit(3)", restart="Never")
        cs.pods.create(pod)
        final = wait_phase(cs, "crasher", t.POD_FAILED)
        term = final.status.container_statuses[0].state.terminated
        assert term.exit_code == 3

    def test_graceful_delete_kills_process(self, node_env):
        cs = node_env["cs"]
        pod = py_pod("longrun", "import time; time.sleep(300)")
        cs.pods.create(pod)
        wait_phase(cs, "longrun", t.POD_RUNNING)
        cs.pods.delete("longrun", grace_seconds=None)  # graceful
        from kubernetes1_tpu.machinery import NotFound

        def gone():
            try:
                cs.pods.get("longrun")
                return False
            except NotFound:
                return True

        must_poll_until(gone, timeout=15.0, desc="pod fully deleted")
        # no leaked sandboxes
        assert not node_env["runtime"].list_pod_sandboxes() or all(
            sb.pod_name != "longrun" for sb in node_env["runtime"].list_pod_sandboxes()
        )

    def test_unhealthy_chip_blocks_future_scheduling(self, node_env):
        cs, impl = node_env["cs"], node_env["impl"]
        impl.set_health("s0-h0-chip0", t.DEVICE_UNHEALTHY)
        must_poll_until(
            lambda: any(
                d.health == t.DEVICE_UNHEALTHY
                for d in (
                    cs.nodes.get("tpu-node-0", "").status.extended_resources or {}
                ).get("google.com/tpu", [])
            ),
            timeout=10.0,
            desc="unhealthy chip visible in node status",
        )
        # only 3 healthy chips remain: a 4-chip ask must pend
        cs.pods.create(py_pod("wants4", "print('hi')", tpus=4))
        time.sleep(1.0)
        assert cs.pods.get("wants4").spec.node_name == ""


class TestRestartSafety:
    def test_assignment_survives_kubelet_restart(self, node_env, tmp_path):
        """The fork's signature behavior: no local checkpoint file — the
        assignment in pod.spec survives kubelet restart, and a second pod
        gets different chips (ref: e2e_node/gpu_device_plugin.go:95-120)."""
        cs, runtime = node_env["cs"], node_env["runtime"]
        pod = py_pod("persist", "import time; time.sleep(300)", tpus=2, restart="Always")
        cs.pods.create(pod)
        wait_phase(cs, "persist", t.POD_RUNNING)
        first = cs.pods.get("persist").spec.extended_resources[0].assigned
        assert len(first) == 2

        node_env["kubelet"].stop()
        kubelet2 = Kubelet(
            cs,
            node_name="tpu-node-0",
            runtime=runtime,  # containers kept running across restart
            plugin_dir=node_env["plugin_dir"],
            heartbeat_interval=0.5,
            sync_interval=0.3,
            pleg_interval=0.3,
        )
        kubelet2.start()
        node_env["kubelet"] = kubelet2
        time.sleep(1.0)
        after = cs.pods.get("persist").spec.extended_resources[0].assigned
        assert after == first  # assignment unchanged (lives in the API object)
        # second pod gets the other chips
        cs.pods.create(py_pod("second", "import time; time.sleep(300)", tpus=2, restart="Always"))
        wait_phase(cs, "second", t.POD_RUNNING)
        second = cs.pods.get("second").spec.extended_resources[0].assigned
        assert not (set(first) & set(second))

    def test_restart_does_not_duplicate_processes(self, node_env):
        """Regression (review-found): kubelet restart must adopt existing
        sandboxes/containers, not spawn duplicates."""
        cs, runtime = node_env["cs"], node_env["runtime"]
        pod = py_pod("adopt", "import time; time.sleep(300)", restart="Always")
        cs.pods.create(pod)
        wait_phase(cs, "adopt", t.POD_RUNNING)
        before = [
            c.id for c in runtime.list_containers()
            if c.state == "RUNNING" and c.name == "main"
        ]
        node_env["kubelet"].stop()
        kubelet2 = Kubelet(
            cs, node_name="tpu-node-0", runtime=runtime,
            plugin_dir=node_env["plugin_dir"],
            heartbeat_interval=0.5, sync_interval=0.3, pleg_interval=0.3,
        )
        kubelet2.start()
        node_env["kubelet"] = kubelet2
        time.sleep(1.5)
        sandboxes = [
            sb for sb in runtime.list_pod_sandboxes() if sb.pod_name == "adopt"
        ]
        running = [
            c.id for c in runtime.list_containers()
            if c.state == "RUNNING"
            and c.sandbox_id in [sb.id for sb in sandboxes]
        ]
        assert len(sandboxes) == 1
        assert running == before  # same single process, adopted not respawned


class TestKubeletServer:
    """Kubelet API server (ref: pkg/kubelet/server/server.go): logs + exec +
    stats over HTTP, endpoint advertised on the Node, consumed by the CLI."""

    def _run_cli(self, master_url, *argv):
        import io

        from kubernetes1_tpu.cli import CLI, build_parser, dispatch

        out = io.StringIO()
        cli = CLI(master_url, "default", out=out)
        args = build_parser().parse_args(["--server", master_url] + list(argv))
        try:
            dispatch(cli, args)
        finally:
            cli.cs.close()
        return out.getvalue()

    def test_ktpu_logs_fetches_container_output(self, node_env):
        cs, master = node_env["cs"], node_env["master"]
        pod = py_pod(
            "chatty",
            "import time; print('training step 1 loss=3.14', flush=True); time.sleep(300)",
            restart="Always",
        )
        cs.pods.create(pod)
        wait_phase(cs, "chatty", t.POD_RUNNING)
        node = cs.nodes.get("tpu-node-0", "")
        assert node.metadata.annotations.get("kubelet.ktpu.io/server")
        # generous timeout: a real python child's interpreter startup can
        # take >10s when the whole suite shares one CPU
        must_poll_until(
            lambda: "loss=3.14" in self._run_cli(master.url, "logs", "chatty"),
            timeout=30.0, desc="logs show container stdout",
        )

    def test_ktpu_exec_runs_in_container_env(self, node_env):
        cs, master = node_env["cs"], node_env["master"]
        pod = py_pod("exec-me", "import time; time.sleep(300)", tpus=1,
                     restart="Always")
        cs.pods.create(pod)
        wait_phase(cs, "exec-me", t.POD_RUNNING)
        # exec runs with the container's injected env: the TPU bootstrap
        # variables the device plugin set are visible inside
        out = self._run_cli(
            master.url, "exec", "exec-me", "--",
            sys.executable, "-c", "import os; print(os.environ['TPU_VISIBLE_CHIPS'])",
        )
        assert out.strip() != ""

    def test_stats_summary_endpoint(self, node_env):
        cs = node_env["cs"]
        pod = py_pod("statsy", "import time; time.sleep(300)", restart="Always")
        cs.pods.create(pod)
        wait_phase(cs, "statsy", t.POD_RUNNING)
        import json
        import urllib.request

        node = cs.nodes.get("tpu-node-0", "")
        base = node.metadata.annotations["kubelet.ktpu.io/server"]
        # the kubelet requires its token on workload endpoints; the
        # apiserver holds it in the node's kube-system secret
        token = node_env["kubelet"].server_token
        req = urllib.request.Request(
            f"{base}/stats/summary",
            headers={"Authorization": f"Bearer {token}"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            summary = json.load(resp)
        assert summary["node"]["nodeName"] == "tpu-node-0"
        pods = {p["pod"]: p for p in summary["pods"]}
        assert "default/statsy" in pods
        must_poll_until(
            lambda: _stats_mem(base, token) > 0, timeout=10.0,
            desc="stats show real memory usage",
        )


def _stats_mem(base, token) -> int:
    import json
    import urllib.request

    req = urllib.request.Request(
        f"{base}/stats/summary", headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        summary = json.load(resp)
    for p in summary["pods"]:
        for c in p["containers"]:
            if c["memory_bytes"] > 0:
                return c["memory_bytes"]
    return 0


class TestInitContainers:
    """Init containers run sequentially to completion before app containers
    (ref: kuberuntime_manager.go computePodActions init gating)."""

    def test_init_sequence_gates_app_container(self, node_env, tmp_path):
        cs = node_env["cs"]
        order = tmp_path / "order.txt"
        pod = t.Pod()
        pod.metadata.name = "with-init"
        pod.spec.restart_policy = "Never"
        pod.spec.init_containers = [
            t.Container(name="init-a", image="img",
                        command=["sh", "-c", f"echo a >> {order}"]),
            t.Container(name="init-b", image="img",
                        command=["sh", "-c", f"echo b >> {order}"]),
        ]
        pod.spec.containers = [
            t.Container(name="main", image="img",
                        command=["sh", "-c", f"echo main >> {order}; sleep 60"]),
        ]
        cs.pods.create(pod)
        wait_phase(cs, "with-init", t.POD_RUNNING, timeout=45)
        # Running means main's PROCESS started; its shell may not have
        # reached the echo yet — poll briefly before judging the order.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "main" not in order.read_text():
            time.sleep(0.05)
        assert order.read_text().split() == ["a", "b", "main"]

    def test_failing_init_fails_pod_with_restart_never(self, node_env):
        cs = node_env["cs"]
        pod = t.Pod()
        pod.metadata.name = "bad-init"
        pod.spec.restart_policy = "Never"
        pod.spec.init_containers = [
            t.Container(name="boom", image="img", command=["sh", "-c", "exit 7"]),
        ]
        pod.spec.containers = [
            t.Container(name="main", image="img", command=["sleep", "60"]),
        ]
        cs.pods.create(pod)
        wait_phase(cs, "bad-init", t.POD_FAILED, timeout=45)
        # the app container was never created AT ALL (any state)
        assert all(c.name != "main"
                   for c in node_env["runtime"].list_containers())

    def test_failing_init_retries_under_onfailure(self, node_env, tmp_path):
        cs = node_env["cs"]
        marker = tmp_path / "attempts"
        pod = t.Pod()
        pod.metadata.name = "retry-init"
        pod.spec.restart_policy = "OnFailure"
        # fails once, then succeeds (state kept on the shared fs)
        pod.spec.init_containers = [
            t.Container(name="flaky", image="img", command=[
                "sh", "-c",
                f"if [ -f {marker} ]; then exit 0; fi; touch {marker}; exit 1",
            ]),
        ]
        pod.spec.containers = [
            t.Container(name="main", image="img", command=["sleep", "60"]),
        ]
        cs.pods.create(pod)
        wait_phase(cs, "retry-init", t.POD_RUNNING, timeout=60)
        assert marker.exists()
