"""Scheduler integration: real apiserver + real scheduler, fake nodes.

Mirrors the reference's test/integration/scheduler suite: nodes are API
objects with synthetic TPU inventories (no kubelet), pods flow through the
real watch -> queue -> schedule -> bind path.
"""

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_node, make_tpu_pod


@pytest.fixture()
def cluster():
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, gang_wait_seconds=5.0)
    sched.start()
    yield master, cs, sched
    sched.stop()
    cs.close()
    master.stop()


def wait_bound(cs, name, ns="default", timeout=10.0):
    def check():
        pod = cs.pods.get(name, ns)
        return bool(pod.spec.node_name)

    must_poll_until(check, timeout=timeout, desc=f"pod {name} bound")
    return cs.pods.get(name, ns)


class TestScheduling:
    def test_cpu_pod_binds(self, cluster):
        _, cs, _ = cluster
        cs.nodes.create(make_node("n1"))
        cs.pods.create(make_tpu_pod("cpu-pod", tpus=0))
        pod = wait_bound(cs, "cpu-pod")
        assert pod.spec.node_name == "n1"

    def test_tpu_pod_gets_device_ids(self, cluster):
        _, cs, _ = cluster
        cs.nodes.create(make_node("n1", tpus=4))
        cs.pods.create(make_tpu_pod("tpu-pod", tpus=2))
        pod = wait_bound(cs, "tpu-pod")
        assert len(pod.spec.extended_resources[0].assigned) == 2
        assert all("tpu" in i for i in pod.spec.extended_resources[0].assigned)

    def test_devices_not_double_allocated(self, cluster):
        _, cs, _ = cluster
        cs.nodes.create(make_node("n1", tpus=4))
        for i in range(2):
            cs.pods.create(make_tpu_pod(f"half-{i}", tpus=2))
        pods = [wait_bound(cs, f"half-{i}") for i in range(2)]
        ids = [i for p in pods for i in p.spec.extended_resources[0].assigned]
        assert len(ids) == 4
        assert len(set(ids)) == 4  # disjoint
        # a fifth chip doesn't exist: next pod stays pending
        cs.pods.create(make_tpu_pod("overflow", tpus=1))
        import time

        time.sleep(1.0)
        assert cs.pods.get("overflow").spec.node_name == ""

    def test_affinity_routes_to_matching_type(self, cluster):
        _, cs, _ = cluster
        cs.nodes.create(make_node("n-v5e", tpus=4, tpu_type="v5e"))
        cs.nodes.create(make_node("n-v5p", tpus=4, tpu_type="v5p", slice_id="slice-p"))
        aff = t.ResourceAffinity(
            required=[
                t.ResourceSelectorRequirement(
                    key=t.ATTR_TPU_TYPE, operator="In", values=["v5p"]
                )
            ]
        )
        cs.pods.create(make_tpu_pod("want-v5p", tpus=2, affinity=aff))
        pod = wait_bound(cs, "want-v5p")
        assert pod.spec.node_name == "n-v5p"

    def test_unschedulable_pod_schedules_after_capacity_arrives(self, cluster):
        _, cs, _ = cluster
        cs.pods.create(make_tpu_pod("waiting", tpus=4))
        import time

        time.sleep(0.5)
        assert cs.pods.get("waiting").spec.node_name == ""
        cs.nodes.create(make_node("late-node", tpus=4))
        pod = wait_bound(cs, "waiting")
        assert pod.spec.node_name == "late-node"


class TestGangScheduling:
    def test_gang_binds_all_or_nothing(self, cluster):
        _, cs, _ = cluster
        # two hosts, same ICI slice, 4 chips each
        cs.nodes.create(make_node("h0", tpus=4, slice_id="v5p-32", host_index=0))
        cs.nodes.create(make_node("h1", tpus=4, slice_id="v5p-32", host_index=1))
        for i in range(2):
            cs.pods.create(
                make_tpu_pod(f"worker-{i}", tpus=4, gang="bert", gang_size=2)
            )
        pods = [wait_bound(cs, f"worker-{i}") for i in range(2)]
        assert {p.spec.node_name for p in pods} == {"h0", "h1"}
        for p in pods:
            assert len(p.spec.extended_resources[0].assigned) == 4

    def test_gang_waits_for_all_members(self, cluster):
        _, cs, _ = cluster
        cs.nodes.create(make_node("h0", tpus=4, slice_id="s", host_index=0))
        cs.nodes.create(make_node("h1", tpus=4, slice_id="s", host_index=1))
        cs.pods.create(make_tpu_pod("lone-0", tpus=4, gang="solo", gang_size=2))
        import time

        time.sleep(1.0)
        assert cs.pods.get("lone-0").spec.node_name == ""  # incomplete gang holds
        cs.pods.create(make_tpu_pod("lone-1", tpus=4, gang="solo", gang_size=2))
        wait_bound(cs, "lone-0")
        wait_bound(cs, "lone-1")

    def test_gang_prefers_single_slice(self, cluster):
        _, cs, _ = cluster
        # slice A: two hosts with 4 free chips each; slice B: two hosts likewise
        # but one host is half-occupied -> only slice A can hold the gang whole
        cs.nodes.create(make_node("a0", tpus=4, slice_id="sliceA", host_index=0))
        cs.nodes.create(make_node("a1", tpus=4, slice_id="sliceA", host_index=1))
        cs.nodes.create(make_node("b0", tpus=4, slice_id="sliceB", host_index=0))
        cs.nodes.create(make_node("b1", tpus=2, slice_id="sliceB", host_index=1))
        for i in range(2):
            cs.pods.create(
                make_tpu_pod(f"g-{i}", tpus=4, gang="affine", gang_size=2)
            )
        pods = [wait_bound(cs, f"g-{i}") for i in range(2)]
        assert {p.spec.node_name for p in pods} == {"a0", "a1"}


class TestPreemption:
    def test_high_priority_preempts(self, cluster):
        _, cs, _ = cluster
        cs.nodes.create(make_node("n1", tpus=4))
        cs.pods.create(make_tpu_pod("victim", tpus=4, priority=0))
        wait_bound(cs, "victim")
        cs.pods.create(make_tpu_pod("vip", tpus=4, priority=100))
        # scheduler preempts: victim gets a graceful deletionTimestamp
        must_poll_until(
            lambda: cs.pods.get("victim").metadata.deletion_timestamp,
            timeout=10.0,
            desc="victim marked for deletion",
        )
        # nominated node recorded on the preemptor
        must_poll_until(
            lambda: cs.pods.get("vip").metadata.annotations.get(
                t.NOMINATED_NODE_ANNOTATION
            )
            == "n1",
            timeout=10.0,
            desc="nominated node annotation",
        )
        # no kubelet in this test: simulate its finalization of the victim
        cs.pods.delete("victim", grace_seconds=0)
        pod = wait_bound(cs, "vip", timeout=15.0)
        assert pod.spec.node_name == "n1"
