"""Unit tests for the runtime lock sanitizer (utils/locksan).

Covers the ISSUE's required matrix: a deliberate A->B / B->A cycle
raises, a consistent global order does not, the hold-time budget fires,
and KTPU_LOCKSAN unset/0 is a true no-op (plain threading primitives)."""

import threading
import time

import pytest

from kubernetes1_tpu.utils import locksan


@pytest.fixture(autouse=True)
def _fresh_graph(monkeypatch):
    """Each test learns lock ordering from scratch, with the sanitizer
    forced on regardless of the outer environment."""
    monkeypatch.setenv("KTPU_LOCKSAN", "1")
    locksan.reset_order_graph()
    yield
    locksan.reset_order_graph()


# ------------------------------------------------------------------ ordering

def test_consistent_order_never_raises():
    a = locksan.make_lock("t.A")
    b = locksan.make_lock("t.B")
    for _ in range(3):
        with a:
            with b:
                pass


def test_ab_ba_cycle_raises():
    a = locksan.make_lock("t.A")
    b = locksan.make_lock("t.B")
    with a:
        with b:
            pass
    with pytest.raises(locksan.LockOrderViolation) as ei:
        with b:
            with a:
                pass
    assert "t.A" in str(ei.value) and "t.B" in str(ei.value)


def test_cycle_detected_across_instances_of_one_class():
    """Two instances sharing a lock NAME are one lock class (lockdep
    model): nesting them is the classic transfer(a, b)/transfer(b, a)
    deadlock and must raise even though the instances differ."""
    a1 = locksan.make_lock("t.Account._lock")
    a2 = locksan.make_lock("t.Account._lock")
    with pytest.raises(locksan.LockOrderViolation):
        with a1:
            with a2:
                pass


def test_three_lock_cycle_raises():
    a = locksan.make_lock("t3.A")
    b = locksan.make_lock("t3.B")
    c = locksan.make_lock("t3.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(locksan.LockOrderViolation):
        with c:
            with a:
                pass


def test_rlock_reentrant_acquire_is_not_a_cycle():
    r = locksan.make_rlock("t.R")
    with r:
        with r:  # same instance re-entry: legal for RLock
            pass


def test_plain_lock_blocking_reacquire_raises_not_freezes():
    """A blocking re-acquire of a non-reentrant Lock this thread already
    holds is a guaranteed deadlock — the sanitizer must report it instead
    of hanging the run (the silent-freeze failure mode it exists for)."""
    a = locksan.make_lock("t.selfdead")
    with pytest.raises(locksan.LockOrderViolation, match="self-deadlock"):
        with a:
            with a:
                pass
    with a:  # released cleanly; reusable
        pass


def test_cycle_detected_between_threads():
    """The dangerous interleaving: thread 1 takes A->B, thread 2 takes
    B->A.  Neither thread alone nests both orders; only the shared graph
    sees the cycle."""
    a = locksan.make_lock("x.A")
    b = locksan.make_lock("x.B")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1, daemon=True)
    th.start()
    th.join(5)
    with pytest.raises(locksan.LockOrderViolation):
        with b:
            with a:
                pass


# ----------------------------------------------------------------- hold time

def test_hold_budget_fires_on_release():
    h = locksan.make_lock("t.H", hold_budget=0.05)
    with pytest.raises(locksan.HoldTimeViolation):
        with h:
            time.sleep(0.12)


def test_hold_violation_never_masks_inflight_exception():
    """An exception already unwinding out of the critical section must
    win over a budget overrun: the real failure is the root cause."""
    h = locksan.make_lock("t.HX", hold_budget=0.05)
    with pytest.raises(ValueError, match="real failure"):
        with h:
            time.sleep(0.12)
            raise ValueError("real failure")
    # the lock is released and reusable afterward
    with h:
        pass


def test_fast_critical_section_within_budget():
    h = locksan.make_lock("t.H2", hold_budget=0.5)
    with h:
        pass


def test_condition_wait_not_charged_as_hold_time():
    """Blocking in Condition.wait releases the lock — a 0.2s budget must
    survive a 0.5s wait, and the post-wakeup critical section is what the
    budget meters."""
    cond = locksan.make_condition(name="t.CW", hold_budget=0.2)

    def waker():
        time.sleep(0.45)
        with cond:
            cond.notify_all()

    th = threading.Thread(target=waker, daemon=True)
    th.start()
    with cond:
        assert cond.wait(5.0)
    th.join(5)


def test_reentrant_condition_wait_not_charged_as_hold_time():
    """Condition.wait on a RE-ENTRANTLY held RLock fully releases every
    recursion level; none of the pre-wait hold may survive into the
    post-wakeup accounting."""
    cond = locksan.make_condition(name="t.nested", hold_budget=0.2)

    def waker():
        time.sleep(0.45)
        with cond:
            cond.notify_all()

    th = threading.Thread(target=waker, daemon=True)
    th.start()
    with cond:
        with cond:  # re-entrant hold before waiting
            assert cond.wait(5.0)
    th.join(5)


def test_trylock_exempt_from_ordering():
    """Non-blocking acquire is the deadlock-AVOIDANCE pattern: it must
    neither raise on a learned reverse order nor poison the graph."""
    a = locksan.make_lock("t.tlA")
    b = locksan.make_lock("t.tlB")
    with a:
        with b:
            pass
    with b:
        got = a.acquire(blocking=False)  # reverse order, but cannot deadlock
        assert got is True
        a.release()
    # the trylock must not have recorded a B->A edge: the learned A->B
    # order still works from a fresh thread without a violation
    errors = []

    def forward():
        try:
            with a:
                with b:
                    pass
        except locksan.LockSanError as e:
            errors.append(e)

    th = threading.Thread(target=forward, daemon=True)
    th.start()
    th.join(5)
    assert not errors, f"trylock poisoned the order graph: {errors[:1]}"


def test_env_budget_default(monkeypatch):
    monkeypatch.setenv("KTPU_LOCKSAN_BUDGET", "0.04")
    h = locksan.make_lock("t.HB")  # no per-lock budget: env applies
    with pytest.raises(locksan.HoldTimeViolation):
        with h:
            time.sleep(0.1)


# ---------------------------------------------------------------- off switch

@pytest.fixture
def _all_sanitizers_off(monkeypatch):
    """The factories return plain primitives only when EVERY sanitizer
    that rides the wrappers is off: locksan itself, schedsan (preemption
    points live on the wrapper), and loopsan (dispatcher lock-wait
    measurement does too — the tier-1 conftest arms it)."""
    from kubernetes1_tpu.utils import loopsan

    monkeypatch.setenv("KTPU_LOCKSAN", "0")
    was = loopsan.active()
    loopsan.deactivate()
    yield
    if was:
        loopsan.activate()


def test_disabled_returns_plain_primitives(monkeypatch, _all_sanitizers_off):
    lock = locksan.make_lock("t.off")
    rlock = locksan.make_rlock("t.off")
    cond = locksan.make_condition(name="t.off")
    assert type(lock) is type(threading.Lock())
    assert type(rlock) is type(threading.RLock())
    assert isinstance(cond, threading.Condition)
    assert not isinstance(cond._lock, locksan._SanBase)
    monkeypatch.delenv("KTPU_LOCKSAN")
    assert type(locksan.make_lock("t.off2")) is type(threading.Lock())


def test_disabled_no_tracking_no_raises(monkeypatch, _all_sanitizers_off):
    a = locksan.make_lock("t.offA")
    b = locksan.make_lock("t.offB")
    with a:
        with b:
            pass
    with b:
        with a:  # would raise if sanitized
            pass


# ------------------------------------------------------- release bookkeeping

def test_out_of_order_release_tracked():
    """Hand-over-hand release order (acquire A, acquire B, release A,
    release B) must keep the per-thread stack coherent."""
    a = locksan.make_lock("t.hhA")
    b = locksan.make_lock("t.hhB")
    a.acquire()
    b.acquire()
    a.release()
    b.release()
    # stack is empty again: a fresh acquisition pair checks cleanly
    with a:
        with b:
            pass


def test_contended_release_retires_own_entry_not_waiters():
    """Regression: release() must retire the RELEASER's bookkeeping before
    freeing the inner lock.  A blind LIFO pop after the release races the
    woken waiter's acquire, leaving stale held-state that produces false
    lock-order edges and misattributed hold times."""
    lock = locksan.make_lock("race.L")
    other = locksan.make_lock("race.M")
    stop = threading.Event()
    errors = []

    def hammer():
        try:
            while not stop.is_set():
                with lock:
                    pass
        except locksan.LockSanError as e:  # pragma: no cover - regression signal
            errors.append(e)

    threads = [threading.Thread(target=hammer, daemon=True) for _ in range(4)]
    for th in threads:
        th.start()
    deadline = time.monotonic() + 1.0
    try:
        while time.monotonic() < deadline:
            with lock:
                pass
            # if a stale entry leaked onto this thread, this nesting would
            # learn a false race.L edge and later raise
            with other:
                pass
    finally:
        stop.set()
        for th in threads:
            th.join(5)
    assert not errors, f"sanitizer raced itself: {errors[:1]}"
    # and the legitimate reverse nesting is still clean (no false edges)
    with other:
        with lock:
            pass


def test_cross_thread_handoff_release_does_not_leak_held_state():
    """acquire-in-A / release-in-B is a legal Lock handoff; afterward
    thread A must not be treated as still holding the lock (no false
    held-class edges, no skipped cycle checks)."""
    h = locksan.make_lock("t.handoff")
    other = locksan.make_lock("t.other")
    h.acquire()
    releaser = threading.Thread(target=h.release, daemon=True)
    releaser.start()
    releaser.join(5)
    # if the handoff leaked, this acquire would add a false
    # t.handoff -> t.other edge from THIS thread's stale stack entry
    with other:
        pass
    with h:  # and this re-acquire would skip cycle checking entirely
        pass
    with other:
        with h:
            pass
    # the (other -> handoff) nesting above must be the only learned edge:
    # the reverse order from a fresh thread proves no stale state
    def reverse():
        with h:
            pass
    th = threading.Thread(target=reverse, daemon=True)
    th.start()
    th.join(5)


def test_trylock_failure_not_recorded_as_held():
    a = locksan.make_lock("t.tryA")
    a.acquire()
    got = a.acquire(blocking=False) if isinstance(a, locksan.SanLock) else False
    assert got is False
    a.release()
    with a:
        pass
