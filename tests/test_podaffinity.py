"""Inter-pod affinity/anti-affinity + selector spreading (ref:
predicates.go:1036 InterPodAffinityMatches, priorities/
selector_spreading.go:43, scheduler integration affinity suites)."""

import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod
from tests.test_controllers import start_hollow_node


@pytest.fixture()
def cluster(tmp_path):
    """4 nodes: 2 on slice s0, 2 on slice s1."""
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, gang_wait_seconds=5.0)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=5.0, eviction_timeout=5.0)
    cm.start()
    nodes = []
    for i in range(4):
        nodes.append(start_hollow_node(
            cs, f"n{i}", str(tmp_path), tpus=4,
            slice_id=f"s{i // 2}", host_index=i % 2,
        ))
    env = {"master": master, "cs": cs, "sched": sched}
    yield env
    for kubelet, plugin, _ in nodes:
        kubelet.stop()
        plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def labeled_pod(name, labels, affinity=None):
    pod = make_tpu_pod(name, tpus=0)
    pod.metadata.labels = labels
    pod.spec.containers[0].command = ["serve"]
    pod.spec.affinity = affinity
    return pod


def wait_scheduled(cs, name, timeout=20.0):
    must_poll_until(
        lambda: bool(cs.pods.get(name, "default").spec.node_name),
        timeout=timeout, desc=f"{name} scheduled",
    )
    return cs.pods.get(name, "default")


def anti_on_host(match_labels):
    return t.Affinity(pod_anti_affinity_required=[
        t.PodAffinityTerm(
            label_selector=t.LabelSelector(match_labels=match_labels),
            topology_key="kubernetes.io/hostname",
        )
    ])


class TestAntiAffinity:
    def test_anti_affinity_pair_never_coschedules(self, cluster):
        cs = cluster["cs"]
        for i in range(4):
            cs.pods.create(labeled_pod(
                f"ha-{i}", {"app": "ha"}, anti_on_host({"app": "ha"})))
        nodes = set()
        for i in range(4):
            nodes.add(wait_scheduled(cs, f"ha-{i}").spec.node_name)
        assert len(nodes) == 4  # one per node, never together
        # a 5th cannot fit anywhere
        cs.pods.create(labeled_pod("ha-4", {"app": "ha"},
                                   anti_on_host({"app": "ha"})))
        time.sleep(3.0)
        assert not cs.pods.get("ha-4", "default").spec.node_name

    def test_symmetry_existing_anti_affinity_blocks_newcomer(self, cluster):
        """An EXISTING pod's required anti-affinity keeps matching pods out
        of its domain, even when the newcomer itself carries no terms."""
        cs = cluster["cs"]
        guard = labeled_pod("guard", {"role": "exclusive"},
                            anti_on_host({"tenant": "other"}))
        cs.pods.create(guard)
        guard_node = wait_scheduled(cs, "guard").spec.node_name
        intruder = labeled_pod("intruder", {"tenant": "other"})
        cs.pods.create(intruder)
        placed = wait_scheduled(cs, "intruder").spec.node_name
        assert placed != guard_node


class TestAffinity:
    def test_affinity_colocates_on_hostname(self, cluster):
        cs = cluster["cs"]
        cs.pods.create(labeled_pod("anchor", {"app": "ps"}))
        anchor_node = wait_scheduled(cs, "anchor").spec.node_name
        follower = labeled_pod("follower", {"app": "worker"}, t.Affinity(
            pod_affinity_required=[t.PodAffinityTerm(
                label_selector=t.LabelSelector(match_labels={"app": "ps"}),
                topology_key="kubernetes.io/hostname",
            )]
        ))
        cs.pods.create(follower)
        assert wait_scheduled(cs, "follower").spec.node_name == anchor_node

    def test_affinity_on_tpu_slice_topology(self, cluster):
        """TPU-native topology: google.com/tpu-slice resolves from device
        attributes — co-locate on the same ICI slice, any host in it."""
        cs = cluster["cs"]
        anchor = labeled_pod("slice-anchor", {"app": "trainer"})
        # pin the anchor to n2 (slice s1) via node selector
        anchor.spec.node_selector = {"kubernetes.io/hostname": "n2"}
        cs.pods.create(anchor)
        assert wait_scheduled(cs, "slice-anchor").spec.node_name == "n2"
        peer = labeled_pod("slice-peer", {"app": "trainer-peer"}, t.Affinity(
            pod_affinity_required=[t.PodAffinityTerm(
                label_selector=t.LabelSelector(match_labels={"app": "trainer"}),
                topology_key="google.com/tpu-slice",
            )]
        ))
        cs.pods.create(peer)
        placed = wait_scheduled(cs, "slice-peer").spec.node_name
        assert placed in ("n2", "n3")  # anywhere on slice s1

    def test_self_colocating_replicas_bootstrap(self, cluster):
        """A workload whose pods require affinity with THEMSELVES must not
        deadlock on replica 1 (upstream's self-match carve-out): the first
        lands anywhere, the rest pile onto its host."""
        cs = cluster["cs"]
        self_aff = t.Affinity(pod_affinity_required=[t.PodAffinityTerm(
            label_selector=t.LabelSelector(match_labels={"app": "flock"}),
            topology_key="kubernetes.io/hostname",
        )])
        for i in range(3):
            cs.pods.create(labeled_pod(f"flock-{i}", {"app": "flock"}, self_aff))
        nodes = {wait_scheduled(cs, f"flock-{i}").spec.node_name
                 for i in range(3)}
        assert len(nodes) == 1  # all co-located after replica 1 bootstraps

    def test_unsatisfiable_affinity_stays_pending(self, cluster):
        cs = cluster["cs"]
        lonely = labeled_pod("lonely", {}, t.Affinity(
            pod_affinity_required=[t.PodAffinityTerm(
                label_selector=t.LabelSelector(match_labels={"app": "ghost"}),
                topology_key="kubernetes.io/hostname",
            )]
        ))
        cs.pods.create(lonely)
        time.sleep(3.0)
        assert not cs.pods.get("lonely", "default").spec.node_name


class TestSelectorSpreading:
    def test_deployment_replicas_spread_across_hosts(self, cluster):
        cs = cluster["cs"]
        dep = t.Deployment()
        dep.metadata.name = "web"
        dep.spec.replicas = 4
        dep.spec.selector = t.LabelSelector(match_labels={"app": "web"})
        dep.spec.template = t.PodTemplateSpec()
        dep.spec.template.metadata.labels = {"app": "web"}
        dep.spec.template.spec.containers = [
            t.Container(name="c", image="x", command=["serve"],
                        resources=t.ResourceRequirements(requests={"cpu": "100m"}))
        ]
        cs.deployments.create(dep)

        def all_placed():
            pods, _ = cs.pods.list(label_selector="app=web")
            return len([p for p in pods if p.spec.node_name]) == 4

        must_poll_until(all_placed, timeout=30.0, desc="4 replicas placed")
        pods, _ = cs.pods.list(label_selector="app=web")
        assert len({p.spec.node_name for p in pods}) == 4, \
            "replicas piled up instead of spreading"


class TestPerfGuard:
    def test_no_checker_built_without_anti_affinity(self, cluster):
        """Plain clusters never pay the O(pods) affinity pass; the tracking
        is a live refcount, not a sticky latch — draining the anti-affinity
        pods returns scheduling to the cheap path."""
        sched = cluster["sched"]
        assert not sched._anti_affinity_uids
        cs = cluster["cs"]
        cs.pods.create(labeled_pod("plain", {"app": "plain"}))
        wait_scheduled(cs, "plain")
        assert not sched._anti_affinity_uids
        cs.pods.create(labeled_pod(
            "flagger", {"app": "f"}, anti_on_host({"app": "f"})))
        wait_scheduled(cs, "flagger")
        assert sched._anti_affinity_uids
        cs.pods.delete("flagger", grace_seconds=0)
        from kubernetes1_tpu.utils.waitutil import must_poll_until

        must_poll_until(lambda: not sched._anti_affinity_uids, timeout=10.0,
                        desc="anti-affinity refcount drains with the pod")
