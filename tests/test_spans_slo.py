"""End-to-end request tracing + pod-startup SLIs (ISSUE 2 tentpole).

The e2e test drives one TPU pod through a LocalCluster and asserts the
acceptance shape: ONE trace id whose spans are retrievable from the
apiserver's, the scheduler's, and the kubelet's /debug/traces, and a
/metrics endpoint exposing the per-phase startup histograms (labels +
cumulative _bucket series) including the TPU device_allocation phase.
"""

import json
import time
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.utils import spans
from kubernetes1_tpu.utils.metrics import MetricsServer, Registry
from kubernetes1_tpu.utils.slo import PHASE_METRIC, StartupSLITracker
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod


def _get(url, token=""):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, r.read()


# ------------------------------------------------------------------ e2e


class TestSpanPropagationE2E:
    def test_one_trace_id_across_apiserver_scheduler_kubelet(self):
        from kubernetes1_tpu.localcluster import LocalCluster

        cluster = LocalCluster(nodes=1).start()
        try:
            cluster.wait_ready()
            pod = make_tpu_pod("traced-pod", tpus=1)
            pod.spec.containers[0].command = ["serve"]
            cluster.cs.pods.create(pod)
            must_poll_until(
                lambda: cluster.cs.pods.get("traced-pod", "default")
                .status.phase == t.POD_RUNNING,
                timeout=30.0, desc="traced pod running")
            live = cluster.cs.pods.get("traced-pod", "default")
            tid = live.metadata.annotations.get(t.TRACE_ID_ANNOTATION)
            assert tid, "apiserver did not stamp the trace id"
            # every SLI phase stamp landed on the object
            for key in (t.CREATED_AT_ANNOTATION, t.SCHEDULED_AT_ANNOTATION,
                        t.BOUND_AT_ANNOTATION, t.ADMITTED_AT_ANNOTATION):
                assert key in live.metadata.annotations, key

            # apiserver leg
            _, raw = _get(cluster.master.url + f"/debug/traces?trace={tid}")
            api_spans = json.loads(raw)["spans"]
            assert any(s["name"].startswith("apiserver.") for s in api_spans)
            assert all(s["traceId"] == tid for s in api_spans)

            # scheduler leg (schedule + bind spans)
            _, raw = _get(cluster.scheduler.metrics_server.url
                          + f"/debug/traces?trace={tid}")
            sch_spans = json.loads(raw)["spans"]
            names = {s["name"] for s in sch_spans}
            assert "scheduler.schedule" in names
            assert "scheduler.bind" in names

            # kubelet leg (device allocation through container start)
            kubelet = cluster.nodes[0].kubelet
            _, raw = _get(kubelet.server.url + f"/debug/traces?trace={tid}",
                          token=kubelet.server_token)
            kl_spans = json.loads(raw)["spans"]
            names = {s["name"] for s in kl_spans}
            assert "kubelet.device_allocation" in names
            assert "kubelet.start_container" in names

            # SLI endpoint: labeled per-phase histograms with _bucket series
            _, raw = _get(cluster.sli.metrics_server.url + "/metrics")
            text = raw.decode()
            for phase in ("scheduled", "bind", "admitted", "running",
                          "total", "device_allocation"):
                assert f'{PHASE_METRIC}_count{{phase="{phase}"}}' in text
            assert f'{PHASE_METRIC}_bucket{{phase="device_allocation",le="+Inf"}}' in text

            # readiness endpoints answer on live components
            status, _ = _get(cluster.scheduler.metrics_server.url + "/readyz")
            assert status == 200
            status, _ = _get(kubelet.server.url + "/readyz")
            assert status == 200
        finally:
            cluster.stop()


# ------------------------------------------------------------------ spans


class TestSpans:
    def test_header_round_trip(self):
        ctx = spans.SpanContext("aaaa", "bbbb")
        assert spans.parse_header(spans.format_context(ctx)) == ctx
        assert spans.parse_header("") is None
        assert spans.parse_header("garbage") is None
        assert spans.parse_header("/half") is None

    def test_span_nesting_and_collection(self):
        col = spans.SpanCollector("test")
        with col.start_span("outer", trace_id="t1") as outer:
            assert spans.current_span() is outer
            assert spans.current_trace_id() == "t1"
            with col.start_span("inner") as inner:
                assert inner.trace_id == "t1"
                assert inner.parent_id == outer.span_id
        assert spans.current_span() is None
        got = col.spans("t1")
        assert [s["name"] for s in got] == ["inner", "outer"]

    def test_exception_exit_records_error(self):
        col = spans.SpanCollector("test")
        with pytest.raises(ValueError):
            with col.start_span("boom"):
                raise ValueError("x")
        assert col.spans()[0]["error"] == "ValueError"

    def test_inject_header_fresh_vs_active(self):
        fresh = spans.parse_header(spans.inject_header())
        assert fresh is not None
        col = spans.SpanCollector("test")
        with col.start_span("op", trace_id="tid9") as sp:
            ctx = spans.parse_header(spans.inject_header())
            assert ctx == spans.SpanContext("tid9", sp.span_id)

    def test_collector_bounded(self):
        col = spans.SpanCollector("test", capacity=4)
        for i in range(10):
            col.start_span(f"s{i}").finish()
        assert len(col.spans()) == 4

    def test_trace_attaches_to_active_span(self):
        from kubernetes1_tpu.utils.trace import Trace

        col = spans.SpanCollector("test")
        lines = []
        with col.start_span("op", trace_id="tr77"):
            with Trace("slow", threshold=0.0, sink=lines.append) as tr:
                tr.step("one")
        assert lines and "trace=tr77" in lines[0]
        assert any("slow: one" in l for l in col.spans()[0]["logs"])


# ---------------------------------------------------------------- metrics


class TestLabeledMetrics:
    def test_counter_labels_render(self):
        reg = Registry()
        c = reg.counter("req_total")
        c.labels(verb="GET").inc(2)
        c.labels(verb="POST").inc()
        out = reg.render()
        assert '# TYPE req_total counter' in out
        assert 'req_total{verb="GET"} 2.0' in out
        assert 'req_total{verb="POST"} 1.0' in out

    def test_histogram_buckets_cumulative(self):
        reg = Registry()
        h = reg.histogram("lat")
        for v in (0.003, 0.02, 0.02, 7.0):
            h.observe(v)
        out = reg.render()
        assert 'lat_bucket{le="0.005"} 1' in out
        assert 'lat_bucket{le="0.025"} 3' in out
        assert 'lat_bucket{le="10.0"} 4' in out
        assert 'lat_bucket{le="+Inf"} 4' in out
        assert 'lat_count 4' in out

    def test_labeled_histogram_merges_label_sets(self):
        reg = Registry()
        h = reg.histogram("phase_s")
        h.labels(phase="bind").observe(0.3)
        out = reg.render()
        assert 'phase_s_bucket{phase="bind",le="0.5"} 1' in out
        assert 'phase_s{phase="bind",quantile="0.5"} 0.300000' in out
        assert 'phase_s_sum{phase="bind"} 0.300000' in out

    def test_same_labels_same_child(self):
        reg = Registry()
        c = reg.counter("x")
        c.labels(a="1").inc()
        c.labels(a="1").inc()
        assert c.labels(a="1").value == 2.0

    def test_registry_type_collision_raises(self):
        reg = Registry()
        reg.counter("m1")
        with pytest.raises(ValueError):
            reg.histogram("m1")
        with pytest.raises(ValueError):
            reg.gauge("m1")
        # same-type lookup still returns the existing metric
        assert reg.counter("m1") is reg.counter("m1")

    def test_register_collision_raises(self):
        from kubernetes1_tpu.utils.metrics import Counter, Histogram

        reg = Registry()
        h = reg.register(Histogram("h1"))
        assert reg.register(h) is h  # same object is fine
        with pytest.raises(ValueError):
            reg.register(Counter("h1"))


class TestReadyz:
    def test_readyz_follows_ready_fn(self):
        state = {"ready": False}
        srv = MetricsServer(Registry(), port=0,
                            ready_fn=lambda: state["ready"]).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url + "/readyz")
            assert ei.value.code == 503
            state["ready"] = True
            status, raw = _get(srv.url + "/readyz")
            assert status == 200 and b"ok" in raw
            # healthz stays unconditionally live
            status, _ = _get(srv.url + "/healthz")
            assert status == 200
        finally:
            srv.stop()

    def test_readyz_default_is_ready(self):
        srv = MetricsServer(Registry(), port=0).start()
        try:
            status, _ = _get(srv.url + "/readyz")
            assert status == 200
        finally:
            srv.stop()

    def test_metrics_server_serves_traces(self):
        col = spans.SpanCollector("comp")
        col.start_span("op", trace_id="abc").finish()
        srv = MetricsServer(Registry(), port=0, spans=col).start()
        try:
            _, raw = _get(srv.url + "/debug/traces?trace=abc")
            doc = json.loads(raw)
            assert doc["component"] == "comp"
            assert [s["name"] for s in doc["spans"]] == ["op"]
        finally:
            srv.stop()


# -------------------------------------------------------------------- SLI


def _sli_pod(name="p1", uid="u1", tpus=1, phase=t.POD_RUNNING, node="n1",
             created=100.0, scheduled=100.5, bound=100.6, admitted=101.0):
    pod = make_tpu_pod(name, tpus=tpus) if tpus else _plain_pod(name)
    pod.metadata.uid = uid
    pod.spec.node_name = node
    pod.status.phase = phase
    ann = pod.metadata.annotations
    if created is not None:
        ann[t.CREATED_AT_ANNOTATION] = f"{created:.6f}"
    if scheduled is not None:
        ann[t.SCHEDULED_AT_ANNOTATION] = f"{scheduled:.6f}"
    if bound is not None:
        ann[t.BOUND_AT_ANNOTATION] = f"{bound:.6f}"
    if admitted is not None:
        ann[t.ADMITTED_AT_ANNOTATION] = f"{admitted:.6f}"
    return pod


def _plain_pod(name):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = "default"
    pod.spec.containers = [t.Container(name="c", image="img")]
    return pod


class _FakeClientset:
    """Just enough for StartupSLITracker.__init__ (informer never started)."""

    class _C:
        scheme = None

        def __getattr__(self, item):
            raise AssertionError("unit test must not hit the API")

    pods = _C()


class TestStartupSLIMath:
    def _tracker(self):
        return StartupSLITracker(_FakeClientset())

    @staticmethod
    def _watch_pending(tr, uid="u1", tpus=1, created=100.0):
        """Replay the real watch sequence's first event: ADDED, Pending,
        unscheduled — what a tracker running since cluster boot sees."""
        tr.record(_sli_pod(uid=uid, tpus=tpus, phase=t.POD_PENDING, node="",
                           created=created, scheduled=None, bound=None,
                           admitted=None), now=created + 0.01)

    def test_phase_decomposition(self):
        tr = self._tracker()
        self._watch_pending(tr)
        pod = _sli_pod()
        tr.record(pod, now=102.0)
        h = tr.phase_seconds

        def one(phase):
            child = h.labels(phase=phase)
            assert child.count == 1, phase
            return child.sum

        assert one("scheduled") == pytest.approx(0.5)
        assert one("bind") == pytest.approx(0.1)
        assert one("admitted") == pytest.approx(0.4)
        assert one("running") == pytest.approx(1.0)
        assert one("total") == pytest.approx(2.0)
        # TPU pod: device_allocation = scheduled-at -> admitted-at
        assert one("device_allocation") == pytest.approx(0.5)
        assert tr.pods_started.value == 1
        assert set(tr.report()) == {
            "scheduled", "bind", "admitted", "running", "total",
            "device_allocation"}

    def test_running_only_counted_once(self):
        tr = self._tracker()
        self._watch_pending(tr)
        pod = _sli_pod()
        tr.record(pod, now=102.0)
        tr.record(pod, now=109.0)  # later resync must not double-observe
        assert tr.phase_seconds.labels(phase="total").count == 1

    def test_non_tpu_pod_has_no_device_phase(self):
        tr = self._tracker()
        self._watch_pending(tr, tpus=0)
        pod = _sli_pod(tpus=0)
        tr.record(pod, now=102.0)
        assert tr.phase_seconds.labels(phase="device_allocation").count == 0
        assert tr.phase_seconds.labels(phase="total").count == 1

    def test_replayed_running_pod_ignored(self):
        tr = self._tracker()
        pod = _sli_pod()
        # first ever sighting is already Running: history replay, skip
        tr.record(pod, now=500.0)
        # identical record for a pod WATCHED through pending first: counted
        pending = _sli_pod(uid="u2", phase=t.POD_PENDING, node="",
                           scheduled=None, bound=None, admitted=None)
        tr.record(pending, now=100.1)
        tr.record(_sli_pod(uid="u2"), now=102.0)
        assert tr.phase_seconds.labels(phase="total").count == 1
        assert tr.pods_started.value == 1

    def test_missing_stamp_skips_phase_not_pod(self):
        tr = self._tracker()
        self._watch_pending(tr, uid="u3")
        pod = _sli_pod(uid="u3", admitted=None)
        tr.record(pod, now=102.0)
        assert tr.phase_seconds.labels(phase="scheduled").count == 1
        assert tr.phase_seconds.labels(phase="admitted").count == 0
        assert tr.phase_seconds.labels(phase="total").count == 1
        # incomplete decomposition: not counted as a fully-tracked start
        assert tr.pods_started.value == 0


class TestTraceExceptionExit:
    def test_exception_exit_always_logs_with_error_step(self):
        from kubernetes1_tpu.utils.trace import Trace

        lines = []
        with pytest.raises(RuntimeError):
            # huge threshold: would never log on the normal path
            with Trace("doomed", threshold=1e9, sink=lines.append) as tr:
                tr.step("prep")
                raise RuntimeError("boom")
        assert len(lines) == 1
        assert "error=RuntimeError" in lines[0] and "prep" in lines[0]

    def test_exception_exit_logs_even_without_threshold(self):
        from kubernetes1_tpu.utils.trace import Trace

        lines = []
        with pytest.raises(KeyError):
            with Trace("doomed2", sink=lines.append):
                raise KeyError("k")
        assert len(lines) == 1 and "error=KeyError" in lines[0]

    def test_clean_exit_still_respects_threshold(self):
        from kubernetes1_tpu.utils.trace import Trace

        lines = []
        with Trace("fast", threshold=1e9, sink=lines.append) as tr:
            tr.step("x")
        assert lines == []


# ------------------------------------------------- watch-cache regression
#
# ISSUE 3 satellite: the trace/SLI pipeline consumes pods through the
# apiserver's watch cache now — the stamps written via the binding
# subresource and the kubelet's admitted-at PATCH must still reach watch
# consumers, in revision order, with nothing skipped or reordered.


class TestSLIStampsThroughWatchCache:
    def test_bind_and_patch_stamps_reach_watchers_in_revision_order(self):
        import threading

        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset

        master = Master().start()
        cs = Clientset(master.url)
        try:
            stream = cs.pods.watch(namespace="default")
            frames = []
            done = threading.Event()

            def drain():
                for _ev_type, obj in stream:
                    frames.append(obj)
                    ann = (obj.get("metadata") or {}).get("annotations") or {}
                    if t.ADMITTED_AT_ANNOTATION in ann:
                        done.set()
                        return

            th = threading.Thread(target=drain, daemon=True)
            th.start()

            pod = make_tpu_pod("sli-watch-pod", tpus=0)
            cs.pods.create(pod)
            # scheduler path: slo./trace. stamps ride the Binding and are
            # merged onto the pod by registry.bind in ONE commit
            binding = t.Binding(target_node="n1")
            binding.metadata.name = "sli-watch-pod"
            binding.metadata.namespace = "default"
            binding.metadata.annotations = {
                t.SCHEDULED_AT_ANNOTATION: f"{time.time():.6f}",
                t.TRACE_ID_ANNOTATION: "cafecafecafecafe",
            }
            cs.bind("default", "sli-watch-pod", binding)
            # kubelet path: admitted-at lands via a metadata PATCH
            cs.pods.patch("sli-watch-pod", {"metadata": {"annotations": {
                t.ADMITTED_AT_ANNOTATION: f"{time.time():.6f}"}}})

            assert done.wait(10), "admitted-at never reached the watcher"
            stream.close()
            th.join(timeout=5)

            revs = [int(o["metadata"]["resourceVersion"]) for o in frames]
            assert revs == sorted(revs), "events out of revision order"
            assert len(set(revs)) == len(revs), "duplicate revisions"
            # the bind commit carries BOTH the merged stamps and bound-at
            bind_frame = next(
                o for o in frames
                if o.get("spec", {}).get("nodeName") == "n1")
            ann = bind_frame["metadata"]["annotations"]
            assert t.SCHEDULED_AT_ANNOTATION in ann
            assert t.BOUND_AT_ANNOTATION in ann
            assert ann[t.TRACE_ID_ANNOTATION] == "cafecafecafecafe"
            # the final frame has the full stamp set, admitted-at included
            final = frames[-1]["metadata"]["annotations"]
            for key in (t.SCHEDULED_AT_ANNOTATION, t.BOUND_AT_ANNOTATION,
                        t.ADMITTED_AT_ANNOTATION):
                assert key in final, key
        finally:
            cs.close()
            master.stop()
