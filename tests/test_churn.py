"""High-churn control plane: the batched deletion pipeline, coalesced
endpoints fan-out, scheduler queue churn hygiene, device-claim release
under mass deletes, and the RL actor-swarm workload.

Contracts under test (the PR 5 group-commit rules, deletion flavor):

1. pods/delete:batch lands N deletions through one store group commit
   with PER-ITEM outcomes — NotFound/Conflict mixed with success, grace/
   finalize semantics preserved per item (amortization, not a
   transaction);
2. batched and singleton deletion produce BYTE-IDENTICAL watch frames
   (separate schemes so the serialization cache cannot mask a
   divergence), and the singleton DELETE wire is unchanged;
3. the endpoints controller with a coalesce window emits ≤ 1 write per
   service per window while the FINAL object equals the uncoalesced
   result; window 0 keeps today's immediate write;
4. a pod deleted while Pending is purged from the scheduling queue and
   the bind-failure counters promptly (counted in
   scheduler_queue_churn_purges_total);
5. device claims and scheduler-cache chip refcounts release promptly
   across a full create→bind→delete→recreate cycle on the SAME chips.
"""

import time

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.apiserver.registry import Registry
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import Conflict, NotFound
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store

from tests.helpers import make_node, make_tpu_pod


def _mk_pod(name, ns="default", uid="", node="", phase=""):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    pod.metadata.uid = uid or f"uid-{name}"
    pod.metadata.creation_timestamp = "2026-01-01T00:00:00Z"
    pod.spec.containers = [t.Container(name="c", image="img")]
    pod.spec.node_name = node
    if phase:
        pod.status.phase = phase
    return pod


class TestDeleteBatchEndpoint:
    def test_per_item_outcomes_mixed(self):
        """One delete:batch request: successes, a NotFound, a stale
        resourceVersion precondition Conflict — each item fails alone."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            for i in range(3):
                p = t.Pod()
                p.metadata.name = f"db-{i}"
                p.spec.containers = [t.Container(name="c", image="i")]
                cs.pods.create(p, "default")
            out = cs.delete_batch("default", [
                "db-0",
                "ghost",
                {"name": "db-1", "resourceVersion": "999999"},
                {"name": "db-2", "gracePeriodSeconds": 0},
            ])
            assert out[0] is None
            assert isinstance(out[1], NotFound)
            assert isinstance(out[2], Conflict)
            assert out[3] is None
            left = {p.metadata.name
                    for p in cs.pods.list(namespace="default")[0]}
            assert left == {"db-1"}  # the Conflict item survived
        finally:
            cs.close()
            master.stop()

    def test_grace_semantics_per_item(self):
        """Bound running pods get deletionTimestamp (the kubelet
        finalizes later); unbound/finished/grace-0 pods go immediately;
        an already-terminating pod is a success no-op."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            reg = master.registry
            for name, node, phase in (
                    ("g-bound", "n1", t.POD_RUNNING),
                    ("g-unbound", "", ""),
                    ("g-done", "n1", t.POD_SUCCEEDED)):
                reg.create("pods", "default",
                           _mk_pod(name, node=node, phase=phase))
            out = cs.delete_batch("default",
                                  ["g-bound", "g-unbound", "g-done"])
            assert out == [None, None, None]
            pods = {p.metadata.name: p
                    for p in cs.pods.list(namespace="default")[0]}
            # only the bound running pod survives, marked terminating
            assert set(pods) == {"g-bound"}
            assert pods["g-bound"].metadata.deletion_timestamp
            # second delete of a terminating pod: success no-op
            out = cs.delete_batch("default", ["g-bound"])
            assert out == [None]
            # grace 0 finalizes it
            out = cs.delete_batch("default", ["g-bound"],
                                  grace_seconds=0)
            assert out == [None]
            assert cs.pods.list(namespace="default")[0] == []
        finally:
            cs.close()
            master.stop()

    def test_cross_namespace_item_forbidden(self):
        """An item naming another namespace is refused — the envelope
        authorized only the URL namespace (the bindings:batch rule)."""
        from kubernetes1_tpu.machinery import ApiError

        master = Master().start()
        cs = Clientset(master.url)
        try:
            try:
                cs.delete_batch("default",
                                [{"name": "x", "namespace": "other"}])
                raise AssertionError("cross-namespace item accepted")
            except ApiError as e:
                assert getattr(e, "code", None) == 403
        finally:
            cs.close()
            master.stop()

    def test_one_group_commit_per_batch(self):
        """N immediate deletes in one request ride ONE store group
        commit (delete-batch occupancy == N)."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            for i in range(6):
                p = t.Pod()
                p.metadata.name = f"oc-{i}"
                p.spec.containers = [t.Container(name="c", image="i")]
                cs.pods.create(p, "default")
            before = master.store.delete_batches
            out = cs.delete_batch("default",
                                  [f"oc-{i}" for i in range(6)])
            assert out == [None] * 6
            assert master.store.delete_batches == before + 1
            assert master.store.delete_batch_ops >= 6
        finally:
            cs.close()
            master.stop()


class TestDeletionWireEquivalence:
    def test_batched_vs_singleton_frames_byte_identical(self, monkeypatch):
        """The same deletion sequence via Registry.delete (singleton) and
        Registry.delete_batch must produce byte-identical watch frames —
        separate stores and schemes so the serialization cache cannot
        mask a divergence.  Covers BOTH legs: immediate delete (DELETED
        frame) and graceful mark (MODIFIED frame with deletionTimestamp,
        pinned via now_iso so a second boundary can't skew the bytes)."""
        from kubernetes1_tpu.apiserver import registry as reg_mod

        monkeypatch.setattr(reg_mod, "now_iso",
                            lambda: "2026-02-02T00:00:00Z")
        stores = [Store(global_scheme.copy()), Store(global_scheme.copy())]
        regs = [Registry(s, s._scheme) for s in stores]
        watchers = [s.watch("/registry/pods/", queue_limit=0)
                    for s in stores]
        try:
            for reg in regs:
                reg.create("pods", "default", _mk_pod("imm"))
                reg.create("pods", "default",
                           _mk_pod("grace", node="n1",
                                   phase=t.POD_RUNNING))
            # singleton leg
            regs[0].delete("pods", "default", "imm")
            regs[0].delete("pods", "default", "grace")
            # batched leg
            out = regs[1].delete_batch("pods", "default", [
                {"name": "imm"}, {"name": "grace"}])
            assert out == [None, None]
            frames = [[], []]
            for i, w in enumerate(watchers):
                while True:
                    ev = w.next_timeout(2)
                    if ev is None:
                        break
                    frames[i].append(
                        stores[i]._scheme.watch_frame_bytes(
                            ev.type, ev.object))
            # 2 creates + 1 DELETED + 1 MODIFIED each, byte-identical
            assert len(frames[0]) == 4
            assert frames[0] == frames[1]
        finally:
            for w in watchers:
                w.stop()
            for s in stores:
                s.close()

    def test_singleton_delete_wire_unchanged(self):
        """The singleton DELETE response body equals the watch DELETED
        frame's object — the default wire carries no new fields."""
        import json as _json

        master = Master().start()
        cs = Clientset(master.url)
        try:
            p = t.Pod()
            p.metadata.name = "wire-0"
            p.spec.containers = [t.Container(name="c", image="i")]
            created = cs.pods.create(p, "default")
            _, rv = cs.pods.list(namespace="default")
            stream = cs.api.watch(
                "/api/v1/namespaces/default/pods",
                {"resourceVersion": str(rv)})
            deleted = cs.pods.delete("wire-0", "default")
            etype, obj = next(iter(stream))
            stream.close()
            assert etype == "DELETED"
            assert _json.dumps(cs.scheme.encode(deleted), sort_keys=True) \
                == _json.dumps(obj, sort_keys=True)
            # the deleted object is the created one at a bumped rv
            assert deleted.metadata.uid == created.metadata.uid
        finally:
            cs.close()
            master.stop()


class TestEndpointsCoalescing:
    def _boot(self, window):
        from kubernetes1_tpu.client import InformerFactory
        from kubernetes1_tpu.controllers import EndpointsController

        master = Master().start()
        cs = Clientset(master.url)
        factory = InformerFactory(cs)
        epc = EndpointsController(cs, factory, coalesce_window=window)
        epc.setup()
        factory.start_all()
        factory.wait_for_sync()
        epc.start_workers()
        return master, cs, factory, epc

    @staticmethod
    def _mk_ready_pod(cs, name, ip):
        pod = _mk_pod(name, node="n1", phase=t.POD_RUNNING)
        pod.metadata.uid = ""
        pod.metadata.labels = {"app": "churny"}
        created = cs.pods.create(pod, "default")
        created.status.phase = t.POD_RUNNING
        created.status.pod_ip = ip
        created.status.conditions = [
            t.PodCondition(type="Ready", status="True")]
        cs.pods.update_status(created)

    def _svc(self):
        svc = t.Service()
        svc.metadata.name = "churny"
        svc.metadata.namespace = "default"
        svc.spec.selector = {"app": "churny"}
        svc.spec.ports = [t.ServicePort(name="p", port=80)]
        return svc

    def test_coalesced_one_write_per_window_and_final_equals_uncoalesced(self):
        """N pod churn events inside one window produce ≤ 1 Endpoints
        write per service per window, the coalesced counter grows, and
        the FINAL object equals what a window-0 (uncoalesced) controller
        computes from the same state."""
        from kubernetes1_tpu.controllers import endpoints as eps_mod

        n = 8
        window = 0.25
        master, cs, factory, epc = self._boot(window)
        try:
            cs.services.create(self._svc(), "default")
            time.sleep(0.1)
            coalesced0 = eps_mod.endpoints_coalesced_total.value
            # count endpoints writes as watch events on the object
            _, rv = cs.resource("endpoints").list(namespace="default")
            stream = cs.api.watch(
                "/api/v1/namespaces/default/endpoints",
                {"resourceVersion": str(rv)})
            t0 = time.monotonic()
            for i in range(n):
                self._mk_ready_pod(cs, f"co-{i}", f"10.0.0.{i + 1}")
            churn_wall = time.monotonic() - t0
            deadline = time.monotonic() + 5 * window + 2.0
            writes = []
            import threading

            def count():
                for etype, _obj in stream:
                    writes.append(etype)

            th = threading.Thread(target=count, daemon=True)
            th.start()
            while time.monotonic() < deadline:
                ep = None
                try:
                    ep = cs.resource("endpoints").get("churny", "default")
                except NotFound:
                    pass
                if ep is not None and sum(
                        len(s.addresses) for s in ep.subsets) == n:
                    break
                time.sleep(0.05)
            time.sleep(2 * window)  # let the last armed flush land
            stream.close()
            ep = cs.resource("endpoints").get("churny", "default")
            ips = sorted(a.ip for s in ep.subsets for a in s.addresses)
            assert ips == sorted(f"10.0.0.{i + 1}" for i in range(n))
            # ≤ 1 write per service per elapsed window (+1 for the
            # window in flight when churn stopped)
            budget = int((churn_wall + 5 * window + 2.0) / window) + 1
            assert 1 <= len(writes) <= budget, (len(writes), budget)
            # the 2n churn events (create + status) minus the armed
            # flushes were absorbed
            assert eps_mod.endpoints_coalesced_total.value > coalesced0
            assert len(writes) < 2 * n
        finally:
            epc.stop()
            factory.stop_all()
            cs.close()
            master.stop()

    def test_window_zero_writes_immediately(self):
        """coalesce_window=0 keeps today's behavior: a pod event flushes
        without waiting a window (and never bumps the coalesced
        counter)."""
        from kubernetes1_tpu.controllers import endpoints as eps_mod

        master, cs, factory, epc = self._boot(0.0)
        try:
            coalesced0 = eps_mod.endpoints_coalesced_total.value
            cs.services.create(self._svc(), "default")
            self._mk_ready_pod(cs, "z-0", "10.0.1.1")
            deadline = time.monotonic() + 5.0
            ep = None
            while time.monotonic() < deadline:
                try:
                    ep = cs.resource("endpoints").get("churny", "default")
                    if any(a.ip == "10.0.1.1"
                           for s in ep.subsets for a in s.addresses):
                        break
                except NotFound:
                    pass
                time.sleep(0.02)
            assert ep is not None
            assert [a.ip for s in ep.subsets for a in s.addresses] \
                == ["10.0.1.1"]
            assert eps_mod.endpoints_coalesced_total.value == coalesced0
        finally:
            epc.stop()
            factory.stop_all()
            cs.close()
            master.stop()

    def test_propagation_lag_observed(self):
        """Every committed write closes the oldest-unserved-event lag
        sample — the propagation SLI the churn bench reports."""
        from kubernetes1_tpu.controllers import endpoints as eps_mod

        master, cs, factory, epc = self._boot(0.05)
        try:
            count0 = eps_mod.endpoints_propagation_seconds.count
            cs.services.create(self._svc(), "default")
            self._mk_ready_pod(cs, "lag-0", "10.0.2.1")
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if eps_mod.endpoints_propagation_seconds.count > count0:
                    break
                time.sleep(0.02)
            assert eps_mod.endpoints_propagation_seconds.count > count0
        finally:
            epc.stop()
            factory.stop_all()
            cs.close()
            master.stop()


class TestSchedulerQueueChurn:
    def test_queue_purge_active_entry(self):
        from kubernetes1_tpu.scheduler.queue import SchedulingQueue

        q = SchedulingQueue()
        q.add("ns/dead")
        q.add("ns/alive")
        assert q.purge("ns/dead") is True
        assert q.purge("ns/dead") is False  # already gone
        assert q.pop(timeout=0.1) == "ns/alive"
        assert q.pop(timeout=0.05) is None  # purged slot never pops
        assert len(q) == 0
        q.shut_down()

    def test_queue_purge_cancels_backoff_timer(self):
        from kubernetes1_tpu.scheduler.queue import SchedulingQueue

        q = SchedulingQueue(base_backoff=0.05, max_backoff=0.05)
        q.add_backoff("ns/backing-off")
        assert q.depth() == 1
        assert q.purge("ns/backing-off") is True
        time.sleep(0.15)  # past the timer: the re-add must not happen
        assert q.pop(timeout=0.05) is None
        assert q.depth() == 0
        q.shut_down()

    def test_scheduler_purges_deleted_pending_pod(self):
        """A pod deleted while Pending leaves the queue, the backoff
        counters, and the bind-fail counters — counted once in
        scheduler_queue_churn_purges_total."""
        from kubernetes1_tpu.scheduler import Scheduler

        master = Master().start()
        cs = Clientset(master.url)
        sched = Scheduler(cs)  # NOT started: handlers driven directly
        try:
            pod = make_tpu_pod("churn-pending", tpus=1)
            pod.metadata.uid = "uid-churn-pending"
            sched._on_pod_add(pod)
            sched._bind_fail_counts[pod.key()] = 3
            assert len(sched.queue) == 1
            sched._on_pod_delete(pod)
            assert sched.queue_churn_purges == 1
            assert len(sched.queue) == 0
            assert pod.key() not in sched._bind_fail_counts
            # idempotent: a duplicate DELETED event purges nothing new
            sched._on_pod_delete(pod)
            assert sched.queue_churn_purges == 1
        finally:
            cs.close()
            master.stop()


class TestDeviceClaimChurnHygiene:
    def test_claims_release_across_batch_delete_recreate_cycle(self):
        """create→bind→delete:batch→recreate on the SAME chips: the
        claim index must release each generation promptly (exact-equality
        against bound pods, no lazy-staleness round-trips needed) and the
        next generation's bind on the same chips must succeed."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.nodes.create(make_node("claim-n1", tpus=4))
            reg = master.registry
            for gen in range(3):
                name = f"claim-pod-g{gen}"
                cs.pods.create(make_tpu_pod(name, tpus=2))
                binding = t.Binding(
                    target_node="claim-n1",
                    extended_resource_assignments={
                        f"{name}-tpu": ["slice-0-h0-tpu0",
                                        "slice-0-h0-tpu1"]})
                binding.metadata.name = name
                binding.metadata.namespace = "default"
                # same two chips every generation: a leaked claim from
                # the previous generation would Conflict here
                cs.bind("default", name, binding)
                with reg._claims_lock:
                    held = set(reg._device_claims)
                assert held == {("claim-n1", "google.com/tpu",
                                 "slice-0-h0-tpu0"),
                                ("claim-n1", "google.com/tpu",
                                 "slice-0-h0-tpu1")}
                out = cs.delete_batch("default", [name], grace_seconds=0)
                assert out == [None]
                with reg._claims_lock:
                    assert not reg._device_claims, \
                        f"claims leaked after gen {gen} batch delete"
        finally:
            cs.close()
            master.stop()

    def test_singleton_delete_releases_claims_eagerly(self):
        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.nodes.create(make_node("claim-n2", tpus=2))
            cs.pods.create(make_tpu_pod("claim-s", tpus=1))
            binding = t.Binding(
                target_node="claim-n2",
                extended_resource_assignments={
                    "claim-s-tpu": ["slice-0-h0-tpu0"]})
            binding.metadata.name = "claim-s"
            binding.metadata.namespace = "default"
            cs.bind("default", "claim-s", binding)
            reg = master.registry
            with reg._claims_lock:
                assert reg._device_claims
            cs.pods.delete("claim-s", "default", grace_seconds=0)
            with reg._claims_lock:
                assert not reg._device_claims
        finally:
            cs.close()
            master.stop()

    def test_cache_refcounts_release_across_cycles(self):
        """Scheduler-cache chip refcounts across repeated
        add→assume→delete cycles on the same chips: availability must
        return to full every generation (the PR 9 refcount + PR 12
        stored-pod-release rules under churn)."""
        from kubernetes1_tpu.scheduler.cache import SchedulerCache

        cache = SchedulerCache()
        cache.update_node(make_node("cy-n1", tpus=2))
        for gen in range(3):
            pod = make_tpu_pod(f"cy-{gen}", tpus=2)
            pod.metadata.uid = f"uid-cy-{gen}"
            assumed = pod.clone()
            assumed.spec.node_name = "cy-n1"
            assumed.spec.extended_resources[0].assigned = [
                "slice-0-h0-tpu0", "slice-0-h0-tpu1"]
            cache.assume_pod(assumed, "cy-n1")
            ni = cache.snapshot()["cy-n1"]
            assert ni.extended[
                "google.com/tpu"].available_count() == 0
            # DELETED arrives (bound version): everything releases
            cache.remove_pod(assumed)
            ni = cache.snapshot()["cy-n1"]
            assert ni.extended[
                "google.com/tpu"].available_count() == 2, \
                f"chips leaked in cache after gen {gen}"


class TestRLActorWorkload:
    def test_rollout_and_learner_loop(self):
        """The actor/learner pairing end to end over HTTP: rollouts
        stream, the learner folds them into policy updates."""
        from kubernetes1_tpu.workloads.rl_actor import Learner, run_actor

        learner = Learner(port=0).start()
        try:
            out = run_actor(learner.url, lifetime_s=0.4,
                            steps_per_batch=32, interval_s=0.01)
            assert out["batches_sent"] > 0
            assert out["errors"] == 0
            stats = learner.stats()
            assert stats["batches"] == out["batches_sent"]
            assert stats["frames"] == out["frames"]
            assert stats["updates"] > 0
        finally:
            learner.stop()

    def test_reinforce_moves_toward_better_arms(self):
        """Sanity on the math: after enough batches the policy weights
        must rank the best arm above the worst (rewards are monotone in
        arm index by construction)."""
        import numpy as np

        from kubernetes1_tpu.workloads.rl_actor import (
            reinforce_update, rollout)

        w = np.zeros(8)
        for i in range(60):
            batch = rollout(w, steps=64, seed=i)
            w, _ = reinforce_update(w, batch)
        assert w[7] > w[0]

    def test_spec_builders_validate(self):
        """The builder objects pass the apiserver's strategies."""
        from kubernetes1_tpu.workloads.rl_actor import (
            actor_pod, fleet_service, learner_job)

        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.pods.create(actor_pod(0, tpus=1, learner_addr="http://x:1"))
            cs.jobs.create(learner_job(workers=2))
            cs.services.create(fleet_service("rl-actors"), "default")
            assert cs.pods.get("actor-0-g0", "default") is not None
        finally:
            cs.close()
            master.stop()


class TestChurnMetricsSurface:
    def test_delete_and_endpoints_metrics_rendered(self):
        import urllib.request

        master = Master().start()
        try:
            with urllib.request.urlopen(master.url + "/metrics",
                                        timeout=5) as r:
                body = r.read().decode()
            for name in ("ktpu_store_delete_batch_occupancy",
                         "ktpu_store_delete_batch_ops_total",
                         "ktpu_endpoints_writes_total",
                         "ktpu_endpoints_coalesced_total",
                         "ktpu_endpoints_propagation_seconds"):
                assert name in body, f"{name} missing from /metrics"
        finally:
            master.stop()

    def test_scheduler_purge_counter_registered(self):
        from kubernetes1_tpu.scheduler import Scheduler

        master = Master().start()
        cs = Clientset(master.url)
        try:
            sched = Scheduler(cs)
            assert "scheduler_queue_churn_purges_total" \
                in sched.metrics.render()
        finally:
            cs.close()
            master.stop()
