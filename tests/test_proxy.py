"""Service networking tests: ClusterIP/NodePort allocation in the
registry and the userspace proxier data plane (ref: pkg/proxy/userspace
proxier tests + pkg/registry/core/service allocator tests)."""

import socket
import socketserver
import threading

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import Forbidden, Invalid
from kubernetes1_tpu.proxy import Proxier
from kubernetes1_tpu.utils.waitutil import must_poll_until


class _Echo(socketserver.BaseRequestHandler):
    def handle(self):
        data = self.request.recv(1024)
        self.request.sendall(self.server.tag + b":" + data)


def start_backend(tag: bytes):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Echo)
    srv.tag = tag
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


@pytest.fixture()
def master():
    m = Master().start()
    cs = Clientset(m.url)
    yield m, cs
    cs.close()
    m.stop()


def make_service(name, port=80, typ="ClusterIP", cluster_ip="", node_port=0,
                 selector=None):
    svc = t.Service()
    svc.metadata.name = name
    svc.spec.type = typ
    svc.spec.cluster_ip = cluster_ip
    svc.spec.selector = selector or {"app": name}
    svc.spec.ports = [t.ServicePort(port=port, target_port=port, node_port=node_port)]
    return svc


class TestAllocation:
    def test_cluster_ip_allocated_and_unique(self, master):
        _, cs = master
        a = cs.services.create(make_service("a"))
        b = cs.services.create(make_service("b"))
        assert a.spec.cluster_ip.startswith("10.96.")
        assert b.spec.cluster_ip.startswith("10.96.")
        assert a.spec.cluster_ip != b.spec.cluster_ip

    def test_explicit_ip_collision_rejected(self, master):
        _, cs = master
        a = cs.services.create(make_service("a"))
        with pytest.raises(Invalid):
            cs.services.create(make_service("b", cluster_ip=a.spec.cluster_ip))

    def test_cluster_ip_immutable(self, master):
        _, cs = master
        a = cs.services.create(make_service("a"))
        a.spec.cluster_ip = "10.96.9.9"
        with pytest.raises(Forbidden):
            cs.services.update(a)

    def test_headless_service(self, master):
        _, cs = master
        h = cs.services.create(make_service("h", cluster_ip="None"))
        assert h.spec.cluster_ip == "None"

    def test_node_port_allocated(self, master):
        _, cs = master
        a = cs.services.create(make_service("a", typ="NodePort"))
        assert 30000 <= a.spec.ports[0].node_port <= 32767
        b = cs.services.create(make_service("b", typ="NodePort"))
        assert b.spec.ports[0].node_port != a.spec.ports[0].node_port

    def test_node_port_collision_rejected(self, master):
        _, cs = master
        cs.services.create(make_service("a", typ="NodePort", node_port=30123))
        with pytest.raises(Invalid):
            cs.services.create(make_service("b", typ="NodePort", node_port=30123))

    def test_bad_type_rejected(self, master):
        _, cs = master
        with pytest.raises(Invalid):
            cs.services.create(make_service("x", typ="LoadBalancer"))

    def test_concurrent_creates_get_unique_ips(self, master):
        _, cs = master
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(8) as ex:
            svcs = list(ex.map(
                lambda i: cs.services.create(make_service(f"s{i}", typ="NodePort")),
                range(16),
            ))
        ips = [s.spec.cluster_ip for s in svcs]
        ports = [s.spec.ports[0].node_port for s in svcs]
        assert len(set(ips)) == 16, f"duplicate clusterIPs: {ips}"
        assert len(set(ports)) == 16, f"duplicate nodePorts: {ports}"

    def test_update_allocates_new_node_port(self, master):
        _, cs = master
        svc = cs.services.create(make_service("a", typ="NodePort"))
        svc.spec.ports.append(t.ServicePort(name="extra", port=81, target_port=81))
        svc.spec.ports[0].name = "main"
        updated = cs.services.update(svc)
        np = [p.node_port for p in updated.spec.ports]
        assert all(30000 <= p <= 32767 for p in np) and len(set(np)) == 2


def put_endpoints(cs, name, backends, port_name=""):
    eps = t.Endpoints()
    eps.metadata.name = name
    eps.subsets = [
        t.EndpointSubset(
            addresses=[t.EndpointAddress(ip=ip) for ip, _ in backends],
            ports=[t.EndpointPort(name=port_name, port=backends[0][1])],
        )
    ]
    try:
        return cs.endpoints.create(eps)
    except Exception:
        cur = cs.endpoints.get(name)
        cur.subsets = eps.subsets
        return cs.endpoints.update(cur)


class TestProxier:
    def test_round_robin_and_vip_resolution(self, master):
        _, cs = master
        s1, p1 = start_backend(b"be1")
        s2, p2 = start_backend(b"be2")
        try:
            svc = cs.services.create(make_service("echo", port=7000))
            # both backends listen on distinct ports; use per-subset ports
            eps = t.Endpoints()
            eps.metadata.name = "echo"
            eps.subsets = [
                t.EndpointSubset(addresses=[t.EndpointAddress(ip="127.0.0.1")],
                                 ports=[t.EndpointPort(port=p1)]),
                t.EndpointSubset(addresses=[t.EndpointAddress(ip="127.0.0.1")],
                                 ports=[t.EndpointPort(port=p2)]),
            ]
            cs.endpoints.create(eps)
            proxier = Proxier(cs).start()
            try:
                must_poll_until(
                    lambda: proxier.resolve(svc.spec.cluster_ip, 7000) is not None,
                    timeout=10.0, desc="vip programmed",
                )
                seen = set()
                for _ in range(6):
                    with proxier.connect(svc.spec.cluster_ip, 7000) as sock:
                        sock.sendall(b"hi")
                        seen.add(sock.recv(1024))
                assert seen == {b"be1:hi", b"be2:hi"}
                assert proxier.stats()["connections"] >= 6
            finally:
                proxier.stop()
        finally:
            s1.shutdown()
            s2.shutdown()

    def test_node_port_listens(self, master):
        _, cs = master
        srv, bp = start_backend(b"np")
        try:
            svc = cs.services.create(make_service("web", port=80, typ="NodePort"))
            put_endpoints(cs, "web", [("127.0.0.1", bp)])
            proxier = Proxier(cs).start()
            try:
                node_port = svc.spec.ports[0].node_port
                must_poll_until(
                    lambda: proxier.node_port_for("default", "web") == node_port,
                    timeout=10.0, desc="nodePort bound",
                )
                with socket.create_connection(("127.0.0.1", node_port), 5) as sock:
                    sock.sendall(b"x")
                    assert sock.recv(1024) == b"np:x"
            finally:
                proxier.stop()
        finally:
            srv.shutdown()

    def test_endpoint_update_and_service_delete(self, master):
        _, cs = master
        s1, p1 = start_backend(b"old")
        s2, p2 = start_backend(b"new")
        try:
            svc = cs.services.create(make_service("flip", port=9000))
            put_endpoints(cs, "flip", [("127.0.0.1", p1)])
            proxier = Proxier(cs).start()
            try:
                must_poll_until(
                    lambda: proxier.resolve(svc.spec.cluster_ip, 9000) is not None,
                    timeout=10.0, desc="vip programmed",
                )
                with proxier.connect(svc.spec.cluster_ip, 9000) as sock:
                    sock.sendall(b"a")
                    assert sock.recv(1024) == b"old:a"
                put_endpoints(cs, "flip", [("127.0.0.1", p2)])

                def flipped():
                    with proxier.connect(svc.spec.cluster_ip, 9000) as sock:
                        sock.sendall(b"b")
                        return sock.recv(1024) == b"new:b"

                must_poll_until(flipped, timeout=10.0, desc="backends flipped")
                cs.services.delete("flip")
                must_poll_until(
                    lambda: proxier.resolve(svc.spec.cluster_ip, 9000) is None,
                    timeout=10.0, desc="vip removed",
                )
            finally:
                proxier.stop()
        finally:
            s1.shutdown()
            s2.shutdown()


class TestRuleTableProxier:
    """iptables-mode analog: compiled rule table, O(1) resolution, no
    per-service sockets (ref: pkg/proxy/iptables/proxier.go)."""

    def _mk_endpoints(self, name, backends):
        eps = t.Endpoints(subsets=[
            t.EndpointSubset(
                addresses=[t.EndpointAddress(ip=ip) for ip, _ in backends],
                ports=[t.EndpointPort(port=backends[0][1])],
            )
        ])
        eps.metadata.name = name
        eps.metadata.namespace = "default"
        return eps

    def test_compiles_and_resolves(self, master):
        from kubernetes1_tpu.proxy import RuleTableProxier

        _, cs = master
        svc = cs.services.create(make_service("rt", port=80))
        cs.endpoints.create(self._mk_endpoints("rt", [("10.0.0.1", 8080),
                                                      ("10.0.0.2", 8080)]))
        proxier = RuleTableProxier(cs)
        proxier.start()
        try:
            must_poll_until(
                lambda: proxier.resolve(svc.spec.cluster_ip, 80) is not None,
                timeout=10.0, desc="table compiled",
            )
            seen = {proxier.resolve(svc.spec.cluster_ip, 80) for _ in range(64)}
            assert seen == {("10.0.0.1", 8080), ("10.0.0.2", 8080)}
            assert proxier.resolve(svc.spec.cluster_ip, 81) is None
            assert proxier.resolve("10.96.99.99", 80) is None
        finally:
            proxier.stop()

    def test_nodeport_and_dump(self, master):
        from kubernetes1_tpu.proxy import RuleTableProxier

        _, cs = master
        svc = cs.services.create(
            make_service("np", port=80, typ="NodePort")
        )
        cs.endpoints.create(self._mk_endpoints("np", [("10.0.0.5", 9000)]))
        proxier = RuleTableProxier(cs)
        proxier.start()
        try:
            node_port = svc.spec.ports[0].node_port or cs.services.get("np").spec.ports[0].node_port
            must_poll_until(
                lambda: proxier.resolve_node_port(node_port) == ("10.0.0.5", 9000),
                timeout=10.0, desc="nodeport rule",
            )
            dump = proxier.dump()
            assert "*nat" in dump and dump.rstrip().endswith("COMMIT")
            assert "KTPU-SERVICES" in dump and "KTPU-SVC-" in dump
            assert f"--dport {node_port}" in dump
            assert "DNAT --to-destination 10.0.0.5:9000" in dump
        finally:
            proxier.stop()

    def test_session_affinity_sticks(self, master):
        from kubernetes1_tpu.proxy import RuleTableProxier

        _, cs = master
        svc = make_service("aff", port=80)
        svc.spec.session_affinity = "ClientIP"
        svc = cs.services.create(svc)
        cs.endpoints.create(self._mk_endpoints("aff", [("10.0.1.1", 80),
                                                       ("10.0.1.2", 80)]))
        proxier = RuleTableProxier(cs)
        proxier.start()
        try:
            must_poll_until(
                lambda: proxier.resolve(svc.spec.cluster_ip, 80, "1.2.3.4") is not None,
                timeout=10.0, desc="compiled",
            )
            first = proxier.resolve(svc.spec.cluster_ip, 80, "1.2.3.4")
            assert all(
                proxier.resolve(svc.spec.cluster_ip, 80, "1.2.3.4") == first
                for _ in range(32)
            )
        finally:
            proxier.stop()

    def test_endpoint_change_triggers_recompile(self, master):
        from kubernetes1_tpu.proxy import RuleTableProxier

        _, cs = master
        svc = cs.services.create(make_service("rc", port=80))
        cs.endpoints.create(self._mk_endpoints("rc", [("10.2.0.1", 80)]))
        proxier = RuleTableProxier(cs)
        proxier.start()
        try:
            must_poll_until(
                lambda: proxier.resolve(svc.spec.cluster_ip, 80) == ("10.2.0.1", 80),
                timeout=10.0, desc="initial",
            )
            fresh = cs.endpoints.get("rc")
            fresh.subsets = self._mk_endpoints("rc", [("10.2.0.9", 80)]).subsets
            cs.endpoints.update(fresh)
            must_poll_until(
                lambda: proxier.resolve(svc.spec.cluster_ip, 80) == ("10.2.0.9", 80),
                timeout=10.0, desc="recompiled",
            )
        finally:
            proxier.stop()


# ------------------------------------------------------------ ipvs mode


class TestIPVSSchedulers:
    def _backends(self, *weights):
        from kubernetes1_tpu.proxy.ipvs import RealServer

        return [RealServer(("10.0.0.%d" % i, 80), w)
                for i, w in enumerate(weights, 1)]

    def test_rr_cycles(self):
        from kubernetes1_tpu.proxy.ipvs import _schedule

        bs = self._backends(1, 1, 1)
        state = [0]
        picks = [_schedule("rr", bs, "1.1.1.1", state).addr for _ in range(6)]
        assert len(set(picks[:3])) == 3 and picks[:3] == picks[3:]

    def test_wrr_proportional(self):
        from collections import Counter

        from kubernetes1_tpu.proxy.ipvs import _schedule

        bs = self._backends(3, 1)
        state = [0]
        got = Counter(_schedule("wrr", bs, "1.1.1.1", state).addr
                      for _ in range(40))
        assert got[("10.0.0.1", 80)] == 30 and got[("10.0.0.2", 80)] == 10

    def test_lc_prefers_fewest_active(self):
        from kubernetes1_tpu.proxy.ipvs import _schedule

        bs = self._backends(1, 1)
        bs[0].active_conns = 5
        assert _schedule("lc", bs, "1.1.1.1", [0]).addr == ("10.0.0.2", 80)

    def test_sh_sticky_per_source(self):
        from kubernetes1_tpu.proxy.ipvs import _schedule

        bs = self._backends(1, 1, 1)
        a = {_schedule("sh", bs, "9.9.9.9", [0]).addr for _ in range(5)}
        b = {_schedule("sh", bs, "8.8.4.4", [0]).addr for _ in range(5)}
        assert len(a) == 1 and len(b) == 1  # deterministic per client

    def test_drained_backend_never_picked(self):
        from kubernetes1_tpu.proxy.ipvs import _schedule

        bs = self._backends(1, 1)
        bs[0].weight = 0
        for _ in range(5):
            assert _schedule("rr", bs, "1.1.1.1", [0]).addr == ("10.0.0.2", 80)


class TestIPVSProxier:
    def test_end_to_end_and_graceful_drain(self):
        import time as _t

        from kubernetes1_tpu.proxy.ipvs import IPVSProxier

        master = Master().start()
        cs = Clientset(master.url)
        b1, p1 = start_backend(b"one")
        b2, p2 = start_backend(b"two")
        try:
            svc = t.Service()
            svc.metadata.name = "ipvs-svc"
            svc.spec.ports = [t.ServicePort(port=8080)]
            cs.services.create(svc)
            ep = t.Endpoints()
            ep.metadata.name = "ipvs-svc"
            ep.subsets = [t.EndpointSubset(
                addresses=[t.EndpointAddress(ip="127.0.0.1")],
                ports=[t.EndpointPort(port=p1)])]
            cs.endpoints.create(ep)

            proxy = IPVSProxier(cs, scheduler="rr").start()
            try:
                svc_live = cs.services.get("ipvs-svc")
                must_poll_until(
                    lambda: proxy.resolve(svc_live.spec.cluster_ip, 8080),
                    timeout=5, desc="vip resolves")
                addr = proxy.resolve(svc_live.spec.cluster_ip, 8080)

                def call():
                    s = socket.create_connection(addr, timeout=5)
                    s.sendall(b"hi")
                    s.shutdown(socket.SHUT_WR)
                    out = s.recv(100)
                    s.close()
                    return out

                assert call() == b"one:hi"
                # add backend two; rr should now hit both
                ep2 = cs.endpoints.get("ipvs-svc")
                ep2.subsets[0].addresses.append(
                    t.EndpointAddress(ip="127.0.0.1"))
                # distinct ports => two subsets
                ep2.subsets = [
                    t.EndpointSubset(
                        addresses=[t.EndpointAddress(ip="127.0.0.1")],
                        ports=[t.EndpointPort(port=p1)]),
                    t.EndpointSubset(
                        addresses=[t.EndpointAddress(ip="127.0.0.1")],
                        ports=[t.EndpointPort(port=p2)]),
                ]
                cs.endpoints.update(ep2)
                must_poll_until(
                    lambda: len((proxy.virtual_for("default", "ipvs-svc")
                                 or type("x", (), {"backends": []})).backends)
                    == 2 or None,
                    timeout=5, desc="both backends present")
                got = {call() for _ in range(8)}
                assert got == {b"one:hi", b"two:hi"}

                # drain: keep an open connection to backend one, then remove
                # it from endpoints — the open conn must survive, new conns
                # must all go to two, and dump() shows the weight-0 drain
                vs = proxy.virtual_for("default", "ipvs-svc")
                # pin a long-lived connection through the virtual server:
                # send nothing yet, so the echo backend blocks in recv and
                # the connection stays active until we speak
                held = None
                for _ in range(10):  # rr: retry until the held conn lands on one
                    cand = socket.create_connection(addr, timeout=5)
                    _t.sleep(0.2)
                    with vs._lock:
                        one = next((b for b in vs.backends
                                    if b.addr == ("127.0.0.1", p1)), None)
                    if one is not None and one.active_conns > 0:
                        held = cand
                        break
                    cand.close()
                    _t.sleep(0.1)
                assert held is not None, "could not pin a connection to backend one"
                ep3 = cs.endpoints.get("ipvs-svc")
                ep3.subsets = [t.EndpointSubset(
                    addresses=[t.EndpointAddress(ip="127.0.0.1")],
                    ports=[t.EndpointPort(port=p2)])]
                cs.endpoints.update(ep3)
                must_poll_until(
                    lambda: all(b.weight > 0 or b.addr == ("127.0.0.1", p1)
                                for b in vs.backends) and
                    any(b.weight == 0 for b in vs.backends) or None,
                    timeout=5, desc="backend one draining at weight 0")
                for _ in range(4):
                    assert call() == b"two:hi"
                # the held connection still completes through the drained
                # backend
                held.sendall(b"hold")
                held.shutdown(socket.SHUT_WR)
                assert held.recv(100) == b"one:hold"
                held.close()
                must_poll_until(
                    lambda: all(b.addr != ("127.0.0.1", p1)
                                for b in vs.backends) or None,
                    timeout=5, desc="drained backend removed after last conn")
                assert "TCP" in proxy.dump()
            finally:
                proxy.stop()
        finally:
            b1.shutdown()
            b2.shutdown()
            cs.close()
            master.stop()
