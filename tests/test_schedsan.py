"""schedsan determinism contract: same seed ⇒ same schedule, per-site
stream independence, identity when inactive — plus the wiring into
locksan and the invariant-probe arming that racesweep relies on."""

import threading

import pytest

from kubernetes1_tpu.utils import invariants, locksan, schedsan


@pytest.fixture(autouse=True)
def _clean_sampler():
    """Every test starts and ends with no active schedule (env-activated
    sessions excepted — then this suite would be testing a live schedule,
    so bail loudly instead of silently flaking)."""
    assert not schedsan.active(), \
        "KTPU_SCHEDSAN is set for this pytest run; schedsan unit tests " \
        "need to own activation"
    yield
    schedsan.deactivate()


def _drive(sites, rounds=400):
    for _ in range(rounds):
        for s in sites:
            schedsan.preempt(s)


class TestDeterminism:
    def test_same_seed_same_trace(self):
        schedsan.activate(42, max_sleep_s=0.0001)
        _drive(["a", "b"])
        first = schedsan.trace()
        stats_first = schedsan.stats()

        schedsan.activate(42, max_sleep_s=0.0001)
        _drive(["a", "b"])
        assert schedsan.trace() == first
        assert schedsan.stats() == stats_first
        # and the schedule actually did something: both non-PROCEED
        # actions appear at the default probabilities over 400 rounds
        actions = {a for _, a in first}
        assert schedsan.YIELD in actions
        assert schedsan.SLEEP in actions

    def test_different_seed_different_trace(self):
        schedsan.activate(1, max_sleep_s=0.0001)
        _drive(["a"])
        one = schedsan.trace()
        schedsan.activate(2, max_sleep_s=0.0001)
        _drive(["a"])
        assert schedsan.trace() != one

    def test_per_site_stream_independence(self):
        """Interleaving calls at other sites must not shift the decision
        sequence one site sees — each site draws from its own stream."""
        schedsan.activate(7, max_sleep_s=0.0001)
        _drive(["a"])
        alone = [t for t in schedsan.trace() if t[0] == "a"]

        schedsan.activate(7, max_sleep_s=0.0001)
        _drive(["b", "a", "c"])  # same "a" call count, noisy neighbors
        interleaved = schedsan.trace(site="a")
        assert interleaved == alone

    def test_seed_exposed_for_replay(self):
        assert schedsan.seed() is None
        schedsan.activate(1729)
        assert schedsan.seed() == 1729
        schedsan.deactivate()
        assert schedsan.seed() is None


class TestIdentityWhenInactive:
    def test_preempt_is_noop(self):
        assert not schedsan.active()
        schedsan.preempt("anything")  # must not raise, allocate a site...
        assert schedsan.stats() == {}  # ...or record anything
        assert schedsan.trace() == []

    def test_locksan_factories_plain_when_both_sanitizers_off(self):
        """schedsan alone must be enough to get sanitized (preempting)
        locks out of the locksan factories, and neither active must mean
        plain primitives — the zero-overhead contract."""
        if locksan.enabled():
            pytest.skip("KTPU_LOCKSAN active: factories always wrap")
        lk = locksan.make_lock("schedsan-test-plain")
        assert isinstance(lk, type(threading.Lock()))
        schedsan.activate(3)
        try:
            wrapped = locksan.make_lock("schedsan-test-wrapped")
            assert not isinstance(wrapped, type(threading.Lock()))
        finally:
            schedsan.deactivate()


class TestPreemptionWiring:
    def test_lock_acquire_release_are_preemption_points(self):
        schedsan.activate(5, max_sleep_s=0.0001)
        lk = locksan.make_lock("schedsan-test-wiring")
        for _ in range(50):
            with lk:
                pass
        sites = set(schedsan.stats())
        assert "lock.acquire:schedsan-test-wiring" in sites
        assert "lock.release:schedsan-test-wiring" in sites

    def test_faultline_check_is_a_preemption_point(self):
        from kubernetes1_tpu.utils import faultline

        schedsan.activate(5, max_sleep_s=0.0001)
        for _ in range(10):
            faultline.check("schedsan.test.site")
        assert "schedsan.test.site" in schedsan.stats()

    def test_trace_is_bounded(self):
        schedsan.activate(5, max_sleep_s=0.0)
        _drive(["x"], rounds=schedsan._TRACE_CAP + 100)
        assert len(schedsan.trace()) == schedsan._TRACE_CAP


class TestInvariantArming:
    def test_armed_by_schedsan(self):
        was = invariants.armed()
        schedsan.activate(11)
        try:
            assert invariants.armed()
        finally:
            schedsan.deactivate()
        assert invariants.armed() == was

    def test_violation_carries_schedsan_seed(self):
        schedsan.activate(99)
        invariants.reset()
        try:
            invariants.rev_monotonic("test.site", "shard0", 10)
            with pytest.raises(invariants.InvariantViolation) as ei:
                invariants.rev_monotonic("test.site", "shard0", 9)
            assert "99" in str(ei.value)  # the reproducing seed, in-band
            assert isinstance(ei.value.flightrecorder, dict)
        finally:
            invariants.reset()
            schedsan.deactivate()
