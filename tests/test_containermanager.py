"""Container manager e2e: cgroup QoS tree, pod/container limits actually
enforced on ProcessRuntime children (kernel OOM kill -> OOMKilled ->
restart), node allocatable, and cgroup-ground-truth stats (ref:
cm/container_manager_linux.go:619, cm/qos_container_manager_linux.go,
test/e2e_node eviction/allocatable suites)."""

import os
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.kubelet import Kubelet, ProcessRuntime
from kubernetes1_tpu.kubelet.containermanager import (
    ContainerManager,
    detect_backend,
    pod_resource_totals,
)
from kubernetes1_tpu.kubelet.eviction import (
    QOS_BESTEFFORT,
    QOS_BURSTABLE,
    QOS_GUARANTEED,
    qos_class,
)
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until


def _pod(name, requests=None, limits=None):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.uid = f"uid-{name}"
    pod.spec.containers = [
        t.Container(
            name="c", image="x", command=["sleep", "1"],
            resources=t.ResourceRequirements(
                requests=requests or {}, limits=limits or {}),
        )
    ]
    return pod


class TestQoSAndTotals:
    def test_qos_classes(self):
        assert qos_class(_pod("be")) == QOS_BESTEFFORT
        assert qos_class(
            _pod("bu", requests={"cpu": "100m"})
        ) == QOS_BURSTABLE
        assert qos_class(
            _pod("gu", requests={"cpu": "1", "memory": "1Gi"},
                 limits={"cpu": "1", "memory": "1Gi"})
        ) == QOS_GUARANTEED
        # limits-only defaults requests := limits -> Guaranteed
        assert qos_class(
            _pod("gl", limits={"cpu": "1", "memory": "1Gi"})
        ) == QOS_GUARANTEED

    def test_pod_resource_totals(self):
        pod = _pod("p", limits={"cpu": "500m", "memory": "128Mi"})
        cpu, mem = pod_resource_totals(pod)
        assert cpu == 500 and mem == 128 * 1024 * 1024
        # any unbounded container -> no pod-level limit for that resource
        pod.spec.containers.append(t.Container(name="c2", image="x"))
        assert pod_resource_totals(pod) == (None, None)

    def test_node_allocatable_reserves(self):
        cm = ContainerManager("n0", backend=None)
        cm.system_reserved = {"cpu": "500m", "memory": "1Gi"}
        alloc = cm.node_allocatable({"cpu": "4", "memory": str(8 << 30), "pods": "110"})
        assert alloc["cpu"] == "3500m"
        assert int(alloc["memory"]) == (8 << 30) - (1 << 30)
        assert alloc["pods"] == "110"


needs_cgroups = pytest.mark.skipif(
    detect_backend("probe").name == "null",
    reason="no writable cgroup hierarchy on this host",
)


@needs_cgroups
class TestCgroupTree:
    def test_qos_tree_and_pod_limits(self, tmp_path):
        cm = ContainerManager("cm-test-node")
        try:
            pod = _pod("limited", limits={"cpu": "250m", "memory": "64Mi"})
            files = cm.container_join_files(pod, pod.spec.containers[0])
            assert files, "expected cgroup.procs join files"
            for pf in files:
                assert pf.endswith("cgroup.procs")
                assert "guaranteed/poduid-limited" in pf
                assert os.path.exists(pf)
            cm.remove_pod_cgroup("uid-limited")
            for pf in files:
                assert not os.path.exists(pf)
        finally:
            cm.cleanup()


@pytest.fixture()
def cg_env(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    runtime = ProcessRuntime(root_dir=str(tmp_path / "ktpu"))
    kubelet = Kubelet(
        cs, node_name="cg-node-0", runtime=runtime,
        plugin_dir=str(tmp_path / "plugins"),
        heartbeat_interval=0.5, sync_interval=0.3, pleg_interval=0.3,
        system_reserved={"cpu": "100m"},
        capacity={"cpu": "8", "memory": str(16 << 30), "pods": "110"},
    )
    kubelet.start()
    env = {"master": master, "cs": cs, "kubelet": kubelet, "runtime": runtime}
    yield env
    kubelet.stop()
    runtime.kill_all()  # containers must not outlive the fixture
    sched.stop()
    cs.close()
    master.stop()


@needs_cgroups
class TestEnforcement:
    def test_memory_limit_oom_kills_and_restarts(self, cg_env):
        """The VERDICT r2 'done' bar: a pod exceeding its memory limit is
        killed (kernel OOM) and restarted; status shows OOMKilled."""
        cs = cg_env["cs"]
        pod = t.Pod()
        pod.metadata.name = "hog"
        pod.spec.restart_policy = "Always"
        pod.spec.containers = [
            t.Container(
                name="hog", image="python",
                command=[sys.executable, "-c",
                         "x = bytearray(256 * 1024 * 1024); import time; time.sleep(60)"],
                resources=t.ResourceRequirements(
                    limits={"cpu": "1", "memory": "48Mi"}),
            )
        ]
        cs.pods.create(pod)

        def oom_observed():
            p = cs.pods.get("hog", "default")
            for cstat in p.status.container_statuses:
                if cstat.state.terminated and cstat.state.terminated.reason == "OOMKilled":
                    return True
                if cstat.restart_count > 0:
                    return True
            return False

        must_poll_until(oom_observed, timeout=30.0, desc="OOM kill + restart")

    def test_within_limit_pod_unharmed_and_cgroup_stats_flow(self, cg_env):
        cs = cg_env["cs"]
        pod = t.Pod()
        pod.metadata.name = "tame"
        pod.spec.restart_policy = "Never"
        pod.spec.containers = [
            t.Container(
                name="tame", image="python",
                command=[sys.executable, "-c",
                         "x = bytearray(8 << 20); import time; time.sleep(8)"],
                resources=t.ResourceRequirements(
                    limits={"cpu": "1", "memory": "256Mi"}),
            )
        ]
        cs.pods.create(pod)
        must_poll_until(
            lambda: cs.pods.get("tame", "default").status.phase == t.POD_RUNNING,
            timeout=20.0, desc="tame running",
        )
        kl = cg_env["kubelet"]
        p = cs.pods.get("tame", "default")

        def cgroup_memory_seen():
            s = kl.container_manager.pod_stats(p.metadata.uid)
            return s is not None and s["memory"] > 8 << 20

        must_poll_until(cgroup_memory_seen, timeout=15.0,
                        desc="cgroup memory ground truth")
        summary = kl.stats_summary()
        entry = next(e for e in summary["pods"] if e["pod"] == "default/tame")
        assert entry["cgroup"]["memory_bytes"] > 8 << 20
        must_poll_until(
            lambda: cs.pods.get("tame", "default").status.phase == t.POD_SUCCEEDED,
            timeout=20.0, desc="tame finishes",
        )

    def test_allocatable_reserved_in_node_status(self, cg_env):
        cs = cg_env["cs"]
        must_poll_until(
            lambda: cs.nodes.get("cg-node-0", "").status.allocatable.get("cpu") == "7900m",
            timeout=10.0, desc="allocatable = capacity - reserved",
        )
        node = cs.nodes.get("cg-node-0", "")
        assert node.status.capacity["cpu"] == "8"
