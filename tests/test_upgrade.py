"""Rolling component upgrade (ref: test/e2e/upgrades/ — every component
restarted in sequence on a live cluster, zero workload disruption).

The "upgrade" here is a rolling restart with the same binary (the repo IS
the version under test); what's being proven is the ORDER and the
contract: the store pair rolls by failover — kill the primary, the
standby promotes, and a FRESH standby attaches to the promoted store so
redundancy is restored within the failover window (the two-member design
cannot pre-attach a standby to a standby, so a bounded single-copy
window during the roll is inherent — a raft quorum is what removes it,
storage/server.py:21); apiservers roll one at a time behind client
failover; the stateless components (KCM, scheduler, kubelets) roll last
— all while a Deployment keeps its replicas running and a Job completes,
with no acknowledged write lost.
"""

import os
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.test_chaos import ChaosCluster, _succeeded, boot_cluster  # noqa: E402


@pytest.fixture()
def cluster(tmp_path, request):
    return boot_cluster(tmp_path, request)


class TestRollingUpgrade:
    def test_rolling_restart_no_disruption(self, cluster):
        c, cs = cluster

        # workloads that must ride through the whole roll
        dep = t.Deployment()
        dep.metadata.name = "ride-along"
        dep.spec.replicas = 2
        dep.spec.selector = t.LabelSelector(match_labels={"app": "ra"})
        tmpl = t.PodTemplateSpec()
        tmpl.metadata.labels = {"app": "ra"}
        tmpl.spec.containers = [t.Container(
            name="c", image="img", command=["sleep", "3600"])]
        dep.spec.template = tmpl
        cs.deployments.create(dep, "default")
        must_poll_until(
            lambda: _running(cs, "app=ra") >= 2,
            timeout=60.0, desc="deployment up before the roll")

        marker = t.ConfigMap(data={"written": "pre-upgrade"})
        marker.metadata.name = "upgrade-marker"
        cs.configmaps.create(marker, "default")

        # ---- phase 1: the store rolls by FAILOVER.  Kill the primary;
        # the standby promotes; immediately attach a fresh standby to the
        # promoted store so the single-copy window stays bounded to the
        # failover itself.
        c.kill("store-primary")
        must_poll_until(
            lambda: "PROMOTED" in open(
                os.path.join(c.d, "store-standby.log")).read(),
            timeout=20.0, desc="standby promoted")
        c.cmds["store-standby-2"] = [
            sys.executable, "-m", "kubernetes1_tpu.storage",
            "--socket", os.path.join(c.d, "s2.sock"),
            "--wal", os.path.join(c.d, "s2.wal"),
            "--standby-of", c.ssock, "--failover-grace", "0.5"]
        c.spawn("store-standby-2")
        # control plane still writes (through failover to the promoted store)
        must_poll_until(
            lambda: _try_write(cs, "during-store-roll"),
            timeout=30.0, desc="writes continue through store roll")
        # redundancy really restored: the new standby's revision CATCHES
        # UP to the promoted store's (not merely >0 — a stalled stream
        # after one record must not pass as 'replicating')
        from kubernetes1_tpu.machinery.scheme import global_scheme
        from kubernetes1_tpu.storage.remote import RemoteStore

        must_poll_until(
            lambda: os.path.exists(os.path.join(c.d, "s2.sock")),
            timeout=20.0, desc="new standby socket up")
        s1 = RemoteStore(global_scheme.copy(), c.ssock)
        s2 = RemoteStore(global_scheme.copy(), os.path.join(c.d, "s2.sock"))

        def caught_up():
            try:
                _try_write(cs, f"repl-probe-{time.monotonic_ns()}")
                return s2.current_revision() >= s1.current_revision() - 2
            except Exception:  # noqa: BLE001 — standby still dialing in
                return False

        try:
            must_poll_until(caught_up, timeout=30.0,
                            desc="new standby caught up to the primary")
        finally:
            s1.close()
            s2.close()

        # ---- phase 2: apiservers, one at a time behind client failover
        for name in ("api-a", "api-b"):
            c.kill(name)
            time.sleep(0.5)
            c.spawn(name)
            must_poll_until(
                lambda: _try_write(cs, f"during-{name}-roll"),
                timeout=30.0, desc=f"writes continue through {name} roll")

        # ---- phase 3: stateless components
        for name in ("kcm", "sched", "kubelet-0", "kubelet-1"):
            c.kill(name)
            time.sleep(0.5)
            c.spawn(name)

        # ---- convergence: a NEW Job completes on the upgraded cluster...
        job = t.Job()
        job.metadata.name = "post-upgrade-job"
        job.spec.completions = 2
        job.spec.parallelism = 2
        jt = t.PodTemplateSpec()
        jt.spec.restart_policy = "Never"
        jt.spec.containers = [t.Container(
            name="w", image="img", command=["sleep", "1"])]
        job.spec.template = jt
        cs.jobs.create(job, "default")
        must_poll_until(
            lambda: _succeeded(cs, "post-upgrade-job") >= 2,
            timeout=240.0, desc="job completes on the upgraded cluster")
        # ...the deployment still has its replicas...
        must_poll_until(
            lambda: _running(cs, "app=ra") >= 2,
            timeout=240.0, desc="deployment intact after the roll")
        # ...and nothing acknowledged was lost
        assert cs.configmaps.get(
            "upgrade-marker", "default").data["written"] == "pre-upgrade"


def _running(cs, selector):
    try:
        pods, _ = cs.pods.list(namespace="default", label_selector=selector)
        return sum(1 for p in pods
                   if p.status.phase == t.POD_RUNNING
                   and not p.metadata.deletion_timestamp)
    except Exception:  # noqa: BLE001
        return 0


def _try_write(cs, name):
    from kubernetes1_tpu.machinery import AlreadyExists

    cm = t.ConfigMap(data={"k": "v"})
    cm.metadata.name = name
    try:
        cs.configmaps.create(cm, "default")
        return True
    except AlreadyExists:
        return True
    except Exception:  # noqa: BLE001
        return False
