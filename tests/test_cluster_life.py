"""Cluster-life scorecard: scorecard-evaluator unit tests + the tier-1
mixer smoke.

The unit half drives obs/scorecard.py and obs/timeline.py with stub
collectors/clientsets (deterministic clocks, no HTTP) and pins the two
staleness invariants from PR 15:

  - a stale PodCustomMetrics collection is MISSING for SLO counting,
    never good or bad;
  - a collector target whose last scrape is older than ``stale_after_s``
    is omitted from the fleet view entirely.

The smoke half is one seconds-scale scripts/cluster_life.py mixer run —
serving + gang + churn + conducted chaos windows on a 2-node
sharded-scheduler topology — asserting the scorecard JSON envelope the
bench and chaos drivers consume.  The full-duration run (node kill, gang
MTTR, induced breach) lives in the slow tier (`chaos.py --schedule
life`).
"""

import time
from types import SimpleNamespace

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.obs import aggregate
from kubernetes1_tpu.obs import timeline as timeline_mod
from kubernetes1_tpu.obs.scorecard import SLO, Scorecard
from kubernetes1_tpu.utils import flightrec


# ------------------------------------------------------------ unit stubs


class _StubTargets:
    """ObsCollector stand-in: targets() only (fleet-view tests)."""

    def __init__(self, targets):
        self._targets = targets

    def targets(self):
        return self._targets


class _StubPCM:
    """clientset.podcustommetrics stand-in (pods-source tests)."""

    def __init__(self, cols):
        self.cols = cols

    def list(self, namespace=None, label_selector=None):
        return self.cols, "1"


def _pcm(value: float, stale: bool = False) -> t.PodCustomMetrics:
    pcm = t.PodCustomMetrics()
    pcm.stale = stale
    pcm.samples = [t.MetricSample(name="ktpu_llama_qps", value=value)]
    return pcm


# ------------------------------------------------------- scorecard units


class TestScorecardStaleness:
    def test_stale_pod_collections_read_missing_not_bad(self):
        """A stale PodCustomMetrics is last-good data wearing a warning
        label; the SLO must not count it as fresh truth in EITHER
        direction.  With one fresh + one stale pod only the fresh value
        is folded; with every pod stale the tick is missing."""
        cs = SimpleNamespace(podcustommetrics=_StubPCM(
            [_pcm(5.0), _pcm(50.0, stale=True)]))
        sc = Scorecard(collector=None, clientset=cs)
        sc.add(SLO(name="qps", source="pods", metric="ktpu_llama_qps",
                   op=">=", threshold=1.0, reduce="max", objective=0.5))
        out = sc.tick(now=100.0)
        assert out["qps"] == 5.0  # the stale 50.0 never enters the fold
        cs.podcustommetrics.cols = [_pcm(5.0, stale=True),
                                    _pcm(50.0, stale=True)]
        out = sc.tick(now=100.5)
        assert out["qps"] is None
        v = sc.verdict()["qps"]
        assert (v["good"], v["bad"], v["missing"]) == (1, 0, 1)

    def test_stale_fleet_targets_omitted_from_view(self):
        """A target the collector has not scraped within stale_after_s
        is dropped from the fleet merge — its series go missing rather
        than freezing at the last scrape's values."""
        parsed = aggregate.parse_metrics_text(
            "# TYPE ktpu_probe gauge\nktpu_probe 1.5\n")
        tgt = SimpleNamespace(parsed=parsed, up=True,
                              last_scrape_mono=99.0)
        sc = Scorecard(collector=_StubTargets([tgt]), clientset=None,
                       stale_after_s=10.0)
        sc.add(SLO(name="probe", source="fleet", metric="ktpu_probe",
                   op="<=", threshold=2.0, objective=0.5))
        assert sc.tick(now=100.0)["probe"] == 1.5  # 1s old: fresh
        assert sc.tick(now=120.0)["probe"] is None  # 21s old: stale
        tgt.up = False
        tgt.last_scrape_mono = 120.0
        assert sc.tick(now=121.0)["probe"] is None  # down: never merged
        v = sc.verdict()["probe"]
        assert (v["good"], v["bad"], v["missing"]) == (1, 0, 2)


class TestScorecardBurnAndBreach:
    def test_fed_breach_fires_hooks_notes_flightrec_and_rearms(self):
        flightrec.reset()
        sc = Scorecard(collector=None, clientset=None)
        sc.add(SLO(name="ops", source="fed", op=">=", threshold=1.0,
                   objective=0.5, scenario="churn",
                   burn_alerts=((1.0, 0.5, 2.0),)))
        hooks = []
        sc.on_breach(lambda slo, ev: hooks.append((slo.name, ev)))
        now = 1000.0
        for i in range(4):  # sustained hard failure: burn = 1/0.5 = 2x
            sc.feed("ops", 0.0)
            sc.tick(now=now + 0.25 * i)
        v = sc.verdict()["ops"]
        assert v["breaches"], "burn 2x over both windows must breach"
        assert hooks and hooks[0][0] == "ops"
        assert hooks[0][1]["burn_rate"] == pytest.approx(2.0)
        kinds = [ev["kind"] for comp in
                 flightrec.dump()["components"].values() for ev in comp]
        assert flightrec.SLO_BREACH in kinds
        # recovery re-arms: good ticks drain the windows, then a second
        # sustained burn is a SECOND breach event, not a suppressed one
        for i in range(8):
            sc.feed("ops", 5.0)
            sc.tick(now=now + 2.0 + 0.25 * i)
        for i in range(4):
            sc.feed("ops", 0.0)
            sc.tick(now=now + 6.0 + 0.25 * i)
        assert len(sc.verdict()["ops"]["breaches"]) == 2
        assert len(hooks) == 2

    def test_burn_rate_exported_under_slo_prefix(self):
        sc = Scorecard(collector=None, clientset=None)
        sc.add(SLO(name="ops", source="fed", op=">=", threshold=1.0,
                   objective=0.5, burn_alerts=((1.0, 0.5, 2.0),)))
        sc.feed("ops", 0.0)
        sc.tick(now=1.0)
        text = sc.render()
        assert "ktpu_slo_burn_rate" in text
        assert 'slo="ops"' in text
        assert "ktpu_slo_bad_total" in text


# -------------------------------------------------------- timeline units


class _StubCollector:
    def __init__(self, flight, spans):
        self._flight = flight
        self._spans = spans

    def flightrecorder(self):
        return {"components": self._flight}

    def traces(self, trace_id=""):
        spans = self._spans
        if trace_id:
            spans = [s for s in spans if s.get("traceId") == trace_id]
        return {"spans": spans}


class TestTimelineMerge:
    def test_events_and_spans_interleave_by_wall_time(self):
        col = _StubCollector(
            flight={
                "scheduler": [{"wall": 10.0, "kind": "gang_attempt",
                               "rv": "41"}],
                "apiserver": [{"wall": 12.0, "kind": "watch_resync",
                               "rv": "41"}],
                "kcm": [{"wall": 11.0, "kind": "node_notready",
                         "node": "node-1"}],
            },
            spans=[{"traceId": "abc", "spanId": "1", "start": 10.5,
                    "durationMs": 30.0, "name": "bind",
                    "component": "scheduler"}])
        tl = timeline_mod.capture(col)
        assert [e["t_wall"] for e in tl["entries"]] == [10.0, 10.5,
                                                        11.0, 12.0]
        assert tl["components"] == ["apiserver", "kcm", "scheduler"]
        assert tl["counts"] == {"events": 3, "spans": 1}
        # correlation keys: the rv links scheduler+apiserver entries,
        # the trace id tags the span
        assert tl["keys"]["rv:41"] == 2
        assert tl["keys"]["trace:abc"] == 1
        # the kcm event's payload survives as detail
        kcm = [e for e in tl["entries"] if e["component"] == "kcm"][0]
        assert kcm["detail"]["node"] == "node-1"

    def test_since_wall_and_max_entries_bound_the_artifact(self):
        col = _StubCollector(
            flight={"c": [{"wall": float(i), "kind": "lease_steal"}
                          for i in range(10)]},
            spans=[])
        tl = timeline_mod.capture(col, since_wall=5.0, max_entries=3)
        assert [e["t_wall"] for e in tl["entries"]] == [7.0, 8.0, 9.0]


# ------------------------------------------------------ the tier-1 smoke


class TestClusterLifeSmoke:
    def test_mini_mix_emits_scorecard_envelope(self):
        """One seconds-scale mixer run: 2 nodes, 2 scheduler shards,
        serving + gang + churn + two conducted fault windows.  Pins the
        scorecard JSON envelope (the contract bench.py, chaos.py and the
        README document) and that every scenario axis got judged."""
        from scripts.cluster_life import LifeConfig, run_cluster_life

        result = run_cluster_life(LifeConfig(
            nodes=2, sched_shards=2, store_shards=1, seed=11,
            solo_seconds=1.0, mix_seconds=5.0,
            serve_impl="synthetic", serve_rate=3.0, serve_replicas=2,
            hpa_max_replicas=3, gang_workers=2, tpus_per_worker=1,
            actors=3, churn_rate=2.0,
            chaos=True, chaos_period_s=2.0, chaos_window_s=0.8,
            node_kill=False))
        # envelope: every consumer-facing key present
        for key in ("config", "seed", "schedsan_seed", "phases", "slos",
                    "breached_slos", "breach_timelines", "interference",
                    "scenarios", "chaos_events", "topology",
                    "slos_measured", "ok"):
            assert key in result, key
        assert result["phases"] == ["boot", "solo:serving", "solo:churn",
                                    "mix"]
        # >=5 SLO verdicts, one per scenario axis
        assert set(result["slos"]) == {
            "serving_p99", "serving_qps", "gang_recovery_mttr",
            "churn_ops", "watch_lag", "hpa_reaction",
            "serving_rollout_errors"}
        for v in result["slos"].values():
            assert {"good", "bad", "missing", "met", "objective",
                    "breaches"} <= set(v)
        # the mix actually measured the live axes (gang MTTR stays
        # missing without a node kill — met None, not a lie)
        measured = [n for n, v in result["slos"].items()
                    if v["good"] + v["bad"] > 0]
        assert len(measured) >= 4, result["slos"]
        assert result["slos"]["gang_recovery_mttr"]["met"] is None
        # interference deltas vs the solo baselines, all three axes
        assert set(result["interference"]) == {
            "serving_p99_s", "watch_lag_p99_s", "churn_ops_per_s"}
        for block in result["interference"].values():
            assert {"solo", "mixed", "delta"} == set(block)
        # chaos windows were conducted and recorded
        assert result["chaos_events"], "no fault window fired"
        assert result["scenarios"]["training"]["gang_reached_running"]
        # the serving phase rode the real L7 path: balancer counters
        # moved, and the mid-mix rollout fed its zero-downtime SLO
        serving = result["scenarios"]["serving"]
        assert serving["balancer"]["requests"] > 0, serving
        rollout_v = result["slos"]["serving_rollout_errors"]
        assert rollout_v["good"] + rollout_v["bad"] > 0, rollout_v
        # a quiet 5s mix with generous thresholds must score green
        assert result["ok"] is True, result["slos"]


@pytest.mark.slow
class TestLifeScheduleSlow:
    def test_chaos_life_schedule_verdict(self):
        """The full mixer as a chaos schedule: node kill + gang MTTR +
        the verdict keys the sweep summary folds."""
        from scripts.chaos import run_life_schedule

        v = run_life_schedule(7, duration=10.0)
        for key in ("ok", "mode", "seed", "acked", "recovery_s",
                    "schedsan_seed", "slos", "interference"):
            assert key in v, key
        assert v["mode"] == "life"
        assert v["ok"] is True, v["slos"]
        assert v["node_killed"]
        assert v["recovery_s"] > 0.0
