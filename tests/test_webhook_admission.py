"""Dynamic admission webhooks (ref: plugin/pkg/admission/webhook +
admissionregistration): mutating patch application, validating denial,
failurePolicy semantics, and self-exemption."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import ApiError


class _WebhookServer:
    """Scriptable admission webhook endpoint."""

    def __init__(self, handler_fn):
        outer = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(n))
                outer.requests.append(review)
                body = json.dumps({"response": handler_fn(review)}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.requests = []
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}/admit"
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture
def env():
    master = Master().start()
    cs = Clientset(master.url)
    yield master, cs
    cs.close()
    master.stop()


def make_pod(name, labels=None):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.labels = labels or {}
    pod.spec.containers = [t.Container(name="c", image="img",
                                       command=["sleep", "1"])]
    return pod


def webhook_config(kind_cls, name, url, resources=("pods",),
                   failure_policy="Fail"):
    cfg = kind_cls()
    cfg.metadata.name = name
    cfg.webhooks = [t.Webhook(
        name=f"{name}.test.ktpu.io", url=url,
        rules=[t.WebhookRule(operations=["CREATE", "UPDATE"],
                             resources=list(resources))],
        failure_policy=failure_policy,
    )]
    return cfg


class TestValidatingWebhook:
    def test_denies_matching_request(self, env):
        _, cs = env

        def handler(review):
            labels = (review["request"]["object"]["metadata"].get("labels")
                      or {})
            if labels.get("forbidden") == "true":
                return {"allowed": False,
                        "status": {"message": "forbidden label"}}
            return {"allowed": True}

        wh = _WebhookServer(handler)
        try:
            cs.resource("validatingwebhookconfigurations").create(
                webhook_config(t.ValidatingWebhookConfiguration,
                               "deny-label", wh.url))
            with pytest.raises(ApiError) as e:
                cs.pods.create(make_pod("bad", labels={"forbidden": "true"}))
            assert "forbidden label" in str(e.value)
            cs.pods.create(make_pod("good"))
            assert wh.requests  # the webhook actually saw the requests
        finally:
            wh.stop()

    def test_failure_policy_fail_rejects_on_dead_url(self, env):
        _, cs = env
        cs.resource("validatingwebhookconfigurations").create(
            webhook_config(t.ValidatingWebhookConfiguration, "dead",
                           "http://127.0.0.1:9/admit",
                           failure_policy="Fail"))
        with pytest.raises(ApiError):
            cs.pods.create(make_pod("p1"))

    def test_failure_policy_ignore_skips_dead_url(self, env):
        _, cs = env
        cs.resource("validatingwebhookconfigurations").create(
            webhook_config(t.ValidatingWebhookConfiguration, "dead-ok",
                           "http://127.0.0.1:9/admit",
                           failure_policy="Ignore"))
        cs.pods.create(make_pod("p2"))  # must succeed

    def test_non_matching_resource_not_called(self, env):
        _, cs = env
        wh = _WebhookServer(lambda review: {"allowed": False})
        try:
            cs.resource("validatingwebhookconfigurations").create(
                webhook_config(t.ValidatingWebhookConfiguration,
                               "pods-only", wh.url, resources=("pods",)))
            cm = t.ConfigMap()
            cm.metadata.name = "untouched"
            cs.configmaps.create(cm)  # not a pod: webhook must not fire
            assert not wh.requests
        finally:
            wh.stop()


class TestMutatingWebhook:
    def test_patch_applied(self, env):
        _, cs = env

        def handler(review):
            return {"allowed": True,
                    "patch": {"metadata": {"annotations":
                                           {"injected": "yes"}}}}

        wh = _WebhookServer(handler)
        try:
            cs.resource("mutatingwebhookconfigurations").create(
                webhook_config(t.MutatingWebhookConfiguration,
                               "inject", wh.url))
            created = cs.pods.create(make_pod("mutated"))
            assert created.metadata.annotations.get("injected") == "yes"
        finally:
            wh.stop()

    def test_webhook_configs_exempt_from_webhooks(self, env):
        """A deny-all validating webhook must not block webhook-config
        management itself (self-lockout prevention)."""
        _, cs = env
        wh = _WebhookServer(lambda review: {"allowed": False})
        try:
            cs.resource("validatingwebhookconfigurations").create(
                webhook_config(t.ValidatingWebhookConfiguration,
                               "deny-all", wh.url, resources=("*",)))
            # still able to create/delete webhook configurations
            cs.resource("mutatingwebhookconfigurations").create(
                webhook_config(t.MutatingWebhookConfiguration,
                               "escape-hatch", wh.url))
            cs.resource("validatingwebhookconfigurations").delete(
                "deny-all", "")
            cs.resource("mutatingwebhookconfigurations").delete(
                "escape-hatch", "")
            cs.pods.create(make_pod("after-removal"))
        finally:
            wh.stop()

    def test_user_info_forwarded(self, env):
        _, cs = env
        seen = {}

        def handler(review):
            seen.update(review["request"].get("userInfo") or {})
            return {"allowed": True}

        wh = _WebhookServer(handler)
        try:
            cs.resource("validatingwebhookconfigurations").create(
                webhook_config(t.ValidatingWebhookConfiguration,
                               "peek", wh.url))
            cs.pods.create(make_pod("who"))
            assert "username" in seen
        finally:
            wh.stop()
