"""CRI over unix socket: RuntimeServer/RemoteRuntime process boundary.

Ref: pkg/kubelet/apis/cri/v1alpha1/runtime/api.proto + pkg/kubelet/remote.
The kubelet must work unchanged against a runtime living behind the socket.
"""

import os
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes1_tpu.kubelet.cri import RemoteRuntime, RuntimeServer
from kubernetes1_tpu.kubelet.runtime import (
    CONTAINER_EXITED,
    CONTAINER_RUNNING,
    ContainerConfig,
    ProcessRuntime,
)


@pytest.fixture
def master_and_client():
    from kubernetes1_tpu.apiserver import Master
    from kubernetes1_tpu.client import Clientset

    master = Master().start()
    cs = Clientset(master.url)
    yield master, cs
    cs.close()
    master.stop()


@pytest.fixture
def remote_fake(tmp_path):
    backend = FakeRuntime()
    server = RuntimeServer(backend, str(tmp_path / "cri.sock"))
    server.start()
    client = RemoteRuntime(server.socket_path)
    yield backend, client
    client.close()
    server.stop()


class TestRemoteRuntime:
    def test_version_roundtrip(self, remote_fake):
        backend, client = remote_fake
        assert client.version() == backend.version()

    def test_sandbox_lifecycle(self, remote_fake):
        _, client = remote_fake
        sid = client.run_pod_sandbox("p", "default", "uid-1")
        boxes = client.list_pod_sandboxes()
        assert [b.id for b in boxes] == [sid]
        assert boxes[0].pod_uid == "uid-1"
        client.stop_pod_sandbox(sid)
        client.remove_pod_sandbox(sid)
        assert client.list_pod_sandboxes() == []

    def test_container_lifecycle_and_status(self, remote_fake):
        _, client = remote_fake
        sid = client.run_pod_sandbox("p", "default", "uid-1")
        cid = client.create_container(
            sid, ContainerConfig(name="c", image="img", command=["sleep", "60"]))
        client.start_container(cid)
        rec = client.container_status(cid)
        assert rec.state == CONTAINER_RUNNING
        client.stop_container(cid, timeout=1.0)
        rec = client.container_status(cid)
        assert rec.state == CONTAINER_EXITED
        assert client.container_status("nope") is None

    def test_error_propagates(self, remote_fake):
        _, client = remote_fake
        with pytest.raises(RuntimeError):
            client.create_container("no-such-sandbox",
                                    ContainerConfig(name="c", image="i"))

    def test_exec_capture(self, remote_fake):
        backend, client = remote_fake
        sid = client.run_pod_sandbox("p", "default", "uid-1")
        cid = client.create_container(
            sid, ContainerConfig(name="c", image="img", command=["sleep", "60"]))
        client.start_container(cid)
        backend.set_exec_result("c", 0)
        code, _ = client.exec_capture(cid, ["true"])
        assert code == 0

    def test_process_runtime_behind_socket(self, tmp_path):
        """A real process started through the socket boundary."""
        backend = ProcessRuntime(root_dir=str(tmp_path / "rt"))
        server = RuntimeServer(backend, str(tmp_path / "cri.sock")).start()
        client = RemoteRuntime(server.socket_path)
        try:
            sid = client.run_pod_sandbox("p", "default", "uid-9")
            marker = str(tmp_path / "marker")
            cid = client.create_container(sid, ContainerConfig(
                name="c", image="img",
                command=["sh", "-c", f"echo done > {marker}"]))
            client.start_container(cid)
            deadline = time.time() + 10
            while time.time() < deadline:
                rec = client.container_status(cid)
                if rec.state == CONTAINER_EXITED:
                    break
                time.sleep(0.1)
            assert rec.exit_code == 0
            assert os.path.exists(marker)
        finally:
            client.close()
            server.stop()
            backend.kill_all()  # containers must not outlive the test


class TestKubeletOverSocket:
    def test_pod_runs_via_remote_runtime(self, tmp_path, master_and_client):
        """Full kubelet sync loop with the runtime across the socket."""
        master, cs = master_and_client
        backend = FakeRuntime()
        server = RuntimeServer(backend, str(tmp_path / "cri.sock")).start()
        client = RemoteRuntime(server.socket_path)
        kl = Kubelet(cs, node_name="cri-node", runtime=client,
                     heartbeat_interval=1.0, sync_interval=0.2,
                     pleg_interval=0.2, server_port=None)
        kl.start()
        try:
            pod = t.Pod()
            pod.metadata.name = "over-socket"
            pod.spec.node_name = "cri-node"
            pod.spec.containers = [
                t.Container(name="c", image="img", command=["sleep", "60"])]
            cs.pods.create(pod)
            deadline = time.time() + 15
            phase = None
            while time.time() < deadline:
                p = cs.pods.get("over-socket")
                phase = p.status.phase
                if phase == t.POD_RUNNING:
                    break
                time.sleep(0.2)
            assert phase == t.POD_RUNNING
            # and the container is genuinely in the backend across the socket
            assert any(c.state == CONTAINER_RUNNING
                       for c in backend.list_containers())
        finally:
            kl.stop()
            client.close()
            server.stop()
