"""Op tracing (utiltrace analog) + /debug/pprof endpoints.

Ref: staging/src/k8s.io/apiserver/pkg/util/trace/trace.go:39 and
net/http/pprof mounted on every reference binary.
"""

import time
import urllib.request

from kubernetes1_tpu.utils.debug import dump_stacks, handle_debug, sample_profile
from kubernetes1_tpu.utils.metrics import MetricsServer, Registry
from kubernetes1_tpu.utils.trace import Trace


class TestTrace:
    def test_silent_under_threshold(self):
        lines = []
        with Trace("fast-op", threshold=10.0, sink=lines.append) as tr:
            tr.step("one")
        assert lines == []

    def test_logs_steps_when_slow(self):
        lines = []
        with Trace("slow-op", threshold=0.0, sink=lines.append, pod="ns/p") as tr:
            tr.step("alpha")
            time.sleep(0.01)
            tr.step("beta")
        assert len(lines) == 1
        out = lines[0]
        assert "slow-op" in out and "pod=ns/p" in out
        assert "alpha" in out and "beta" in out

    def test_no_threshold_never_logs(self):
        lines = []
        with Trace("op", sink=lines.append) as tr:
            tr.step("x")
        assert lines == []

    def test_explicit_log_if_long_threshold(self):
        lines = []
        tr = Trace("op", sink=lines.append)
        tr.step("x")
        tr.log_if_long(0.0)
        assert len(lines) == 1


class TestDebugHandlers:
    def test_stacks_contains_this_thread(self):
        out = dump_stacks()
        assert "test_stacks_contains_this_thread" in out

    def test_profile_samples(self):
        out = sample_profile(0.05, hz=200.0)
        assert out.startswith("samples:")

    def test_handle_debug_routes(self):
        assert handle_debug("/metrics", {}) is None
        status, _, body = handle_debug("/debug/pprof", {})
        assert status == 200 and b"stacks" in body
        status, _, _ = handle_debug("/debug/pprof/stacks", {})
        assert status == 200
        status, _, _ = handle_debug("/debug/pprof/unknown", {})
        assert status == 404

    def test_handle_debug_seconds_scalar_and_list(self):
        for q in ({"seconds": "0.05"}, {"seconds": ["0.05"]}):
            status, _, body = handle_debug("/debug/pprof/profile", q)
            assert status == 200 and body.startswith(b"samples:")


class TestServedEndpoints:
    def test_metrics_server_serves_debug(self):
        srv = MetricsServer(Registry(), port=0).start()
        try:
            with urllib.request.urlopen(srv.url + "/debug/pprof/stacks") as r:
                assert r.status == 200
                assert b"thread" in r.read()
        finally:
            srv.stop()

    def test_apiserver_serves_debug(self):
        from kubernetes1_tpu.apiserver import Master

        master = Master().start()
        try:
            with urllib.request.urlopen(master.url + "/debug/pprof/stacks") as r:
                assert r.status == 200
                assert b"thread" in r.read()
        finally:
            master.stop()

    def test_scheduler_trace_logs_slow_attempt(self, monkeypatch):
        """A slow schedule() emits its step breakdown through the sink."""
        from kubernetes1_tpu.utils import trace as trace_mod

        lines = []
        monkeypatch.setattr(trace_mod, "trace_sink", lines.append)
        from kubernetes1_tpu.api import types as t
        from kubernetes1_tpu.scheduler import scheduler as sched_mod

        monkeypatch.setattr(sched_mod, "TRACE_THRESHOLD_S", 0.0)
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset
        from kubernetes1_tpu.scheduler import Scheduler

        master = Master().start()
        cs = Clientset(master.url)
        sched = Scheduler(cs)
        sched.start()
        try:
            node = t.Node()
            node.metadata.name = "n1"
            node.status.capacity = {"cpu": "4", "memory": "8Gi", "pods": "10"}
            node.status.allocatable = dict(node.status.capacity)
            node.status.conditions = [
                t.NodeCondition(type="Ready", status="True")]
            cs.nodes.create(node)
            pod = t.Pod()
            pod.metadata.name = "traced"
            pod.spec.containers = [
                t.Container(name="c", image="img", command=["sleep"])]
            cs.pods.create(pod)
            deadline = time.time() + 10
            while time.time() < deadline:
                p = cs.pods.get("traced")
                if p.spec.node_name:
                    break
                time.sleep(0.05)
            assert p.spec.node_name == "n1"
            deadline = time.time() + 2
            while time.time() < deadline and not lines:
                time.sleep(0.05)
            assert any("scheduling" in ln and "feasible" in ln for ln in lines)
        finally:
            sched.stop()
            cs.close()
            master.stop()
