"""`ktpu init` / `ktpu join` two-host bootstrap e2e (ref: cmd/kubeadm
init/join phases + the kubelet TLS-bootstrap CSR flow).

The VERDICT r3 'done' bar: a two-host cluster bootstrapped from two shell
commands — real binaries, real ports, Node,RBAC authorization, CSR-issued
node credentials."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import ApiError, Unauthorized
from kubernetes1_tpu.utils.waitutil import must_poll_until

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_ktpu(*argv, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "kubernetes1_tpu.cli", *argv],
        capture_output=True, timeout=timeout, text=True,
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
        cwd=REPO,
    )


@pytest.fixture()
def two_host_cluster(tmp_path):
    """init on 'host1' (dir1), join as 'host2' (dir2) — one machine, two
    kubelet identities, exactly the two commands an operator runs."""
    port = free_port()
    d1, d2 = str(tmp_path / "host1"), str(tmp_path / "host2")
    r = run_ktpu("init", "--dir", d1, "--port", str(port),
                 "--node-name", "host1")
    assert r.returncode == 0, r.stdout + r.stderr
    # the join command is printed verbatim; parse it like an operator would
    join_line = next(line for line in r.stdout.splitlines()
                     if "ktpu join" in line).strip()
    parts = join_line.split()
    server = parts[parts.index("--server") + 1]
    token = parts[parts.index("--token") + 1]
    ca_hash = parts[parts.index("--ca-cert-hash") + 1]
    r2 = run_ktpu("join", "--server", server, "--token", token,
                  "--ca-cert-hash", ca_hash,
                  "--node-name", "host2", "--dir", d2)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    env = {"server": server, "token": token, "ca_hash": ca_hash,
           "admin_conf": os.path.join(d1, "admin.conf"),
           "d1": d1, "d2": d2, "init_out": r.stdout}
    yield env
    for d in (d1, d2):
        try:
            pids = json.load(open(os.path.join(d, "pids.json")))
        except OSError:
            continue
        for pid in pids.values():
            try:
                os.killpg(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass


class TestInitJoin:
    def test_two_hosts_ready_and_secured(self, two_host_cluster):
        env = two_host_cluster
        assert env["server"].startswith("https://")
        admin = Clientset.from_config(env["admin_conf"])
        try:
            def both_ready():
                try:
                    nodes, _ = admin.nodes.list()
                except ApiError:
                    return False
                ready = {n.metadata.name for n in nodes
                         if any(c.type == "Ready" and c.status == "True"
                                for c in n.status.conditions)}
                return {"host1", "host2"} <= ready

            must_poll_until(both_ready, timeout=30.0, desc="both hosts Ready")
            # both kubelets joined via CSR-signed credentials (kubeadm-style
            # random-suffix names: node-csr-<node>-<rand>)
            csrs, _ = admin.certificatesigningrequests.list()
            names = {c.metadata.name for c in csrs}
            for node in ("host1", "host2"):
                assert any(n.startswith(f"node-csr-{node}-") for n in names)
            for c in csrs:
                assert c.status.certificate  # approved + signed
            # anonymous access is locked down (Node,RBAC mode) — verified
            # TLS, no credential
            anon = Clientset(env["server"],
                             ca_file=os.path.join(env["d1"], "pki", "ca.crt"))
            with pytest.raises(ApiError):
                anon.pods.list()
            anon.close()
            # a pod schedules and runs across the bootstrapped cluster
            from kubernetes1_tpu.api import types as t

            pod = t.Pod()
            pod.metadata.name = "hello"
            pod.spec.restart_policy = "Never"
            pod.spec.containers = [t.Container(
                name="c", image="python",
                command=[sys.executable, "-c", "print('bootstrapped')"])]
            admin.pods.create(pod)
            must_poll_until(
                lambda: admin.pods.get("hello", "default").status.phase
                == "Succeeded",
                timeout=40.0, desc="workload runs on the bootstrapped cluster",
            )
            # control-plane manifests written (the restartable record)
            manifests = os.listdir(os.path.join(env["d1"], "manifests"))
            assert {"kube-apiserver.json", "kube-scheduler.json",
                    "kube-controller-manager.json"} <= set(manifests)
            # ---- zero plaintext sockets (VERDICT r3 #1 'done' bar) ----
            # the apiserver port does not speak plaintext HTTP
            import http.client as _http
            from urllib.parse import urlparse as _up

            parsed = _up(env["server"])
            with pytest.raises((OSError, _http.HTTPException)):
                c = _http.HTTPConnection(parsed.hostname, parsed.port,
                                         timeout=5)
                c.request("GET", "/healthz")
                c.getresponse()
            # every kubelet advertises an HTTPS endpoint, and that port
            # refuses plaintext too
            nodes, _ = admin.nodes.list()
            for n in nodes:
                kurl = (n.metadata.annotations or {}).get(
                    "kubelet.ktpu.io/server", "")
                assert kurl.startswith("https://"), \
                    f"{n.metadata.name} kubelet serves plaintext: {kurl}"
                kp = _up(kurl)
                with pytest.raises((OSError, _http.HTTPException)):
                    c = _http.HTTPConnection(kp.hostname, kp.port, timeout=5)
                    c.request("GET", "/healthz")
                    c.getresponse()
            # exec works END TO END over the TLS hops (client → apiserver
            # → kubelet, both TLS): run a command in a fresh pod
            sleeper = t.Pod()
            sleeper.metadata.name = "tls-exec"
            sleeper.spec.containers = [t.Container(
                name="c", image="python",
                command=[sys.executable, "-c",
                         "import time; time.sleep(30)"])]
            admin.pods.create(sleeper)
            must_poll_until(
                lambda: admin.pods.get("tls-exec", "default").status.phase
                == "Running",
                timeout=40.0, desc="exec target pod running")
            r = run_ktpu("--kubeconfig", env["admin_conf"],
                         "exec", "tls-exec", "--", "echo", "over-tls",
                         timeout=30)
            assert r.returncode == 0, r.stdout + r.stderr
            assert "over-tls" in r.stdout
        finally:
            admin.close()

    def test_join_with_bad_token_fails(self, two_host_cluster):
        env = two_host_cluster
        r = run_ktpu("join", "--server", env["server"], "--token",
                     "deadbe.0000000000000000", "--node-name", "intruder",
                     "--dir", env["d2"] + "-x", timeout=60)
        assert r.returncode != 0
        out = (r.stdout + r.stderr).lower()
        # a bad token now dies at the earliest gate: token-discovery of the
        # cluster CA (presented-but-invalid credentials are rejected even
        # for the anonymous-readable cluster-info)
        assert ("csr create failed" in out or "unauthorized" in out
                or "forbidden" in out or "invalid bearer token" in out
                or "discovery failed" in out)
