"""PodPreset admission (ref: plugin/pkg/admission/podpreset/admission.go,
settings.k8s.io/v1alpha1): declarative injection into matching pods."""

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset


@pytest.fixture
def env():
    master = Master().start()
    cs = Clientset(master.url)
    yield master, cs
    cs.close()
    master.stop()


def make_preset(name, selector_labels, env=None, volumes=None, mounts=None):
    p = t.PodPreset()
    p.metadata.name = name
    p.spec.selector = t.LabelSelector(match_labels=selector_labels)
    p.spec.env = env or []
    p.spec.volumes = volumes or []
    p.spec.volume_mounts = mounts or []
    return p


def make_pod(name, labels=None, env=None):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.labels = labels or {}
    c = t.Container(name="train", image="jax", command=["sleep", "1"])
    c.env = env or []
    pod.spec.containers = [c]
    return pod


class TestPodPreset:
    def test_injects_env_and_volumes(self, env):
        _, cs = env
        cs.resource("podpresets").create(make_preset(
            "tpu-defaults", {"role": "train"},
            env=[t.EnvVar(name="CKPT_DIR", value="/ckpt")],
            volumes=[t.Volume(name="ckpt",
                              empty_dir=t.EmptyDirVolumeSource())],
            mounts=[t.VolumeMount(name="ckpt", mount_path="/ckpt")],
        ))
        created = cs.pods.create(make_pod("worker", {"role": "train"}))
        c = created.spec.containers[0]
        assert any(e.name == "CKPT_DIR" and e.value == "/ckpt" for e in c.env)
        assert any(m.name == "ckpt" and m.mount_path == "/ckpt"
                   for m in c.volume_mounts)
        assert any(v.name == "ckpt" for v in created.spec.volumes)
        assert any(k.startswith("podpreset.admission.ktpu.io/podpreset-")
                   for k in created.metadata.annotations)

    def test_non_matching_pod_untouched(self, env):
        _, cs = env
        cs.resource("podpresets").create(make_preset(
            "tpu-defaults", {"role": "train"},
            env=[t.EnvVar(name="CKPT_DIR", value="/ckpt")]))
        created = cs.pods.create(make_pod("other", {"role": "serve"}))
        assert not any(e.name == "CKPT_DIR"
                       for e in created.spec.containers[0].env)

    def test_conflict_skips_whole_preset(self, env):
        _, cs = env
        cs.resource("podpresets").create(make_preset(
            "tpu-defaults", {"role": "train"},
            env=[t.EnvVar(name="CKPT_DIR", value="/ckpt"),
                 t.EnvVar(name="EXTRA", value="yes")]))
        created = cs.pods.create(make_pod(
            "conflicted", {"role": "train"},
            env=[t.EnvVar(name="CKPT_DIR", value="/elsewhere")]))
        c = created.spec.containers[0]
        # the user's value wins AND nothing else from the preset lands
        assert [e.value for e in c.env if e.name == "CKPT_DIR"] == ["/elsewhere"]
        assert not any(e.name == "EXTRA" for e in c.env)
        assert any(k.startswith("podpreset.admission.ktpu.io/conflict-")
                   for k in created.metadata.annotations)

    def test_exclude_annotation(self, env):
        _, cs = env
        cs.resource("podpresets").create(make_preset(
            "tpu-defaults", {"role": "train"},
            env=[t.EnvVar(name="CKPT_DIR", value="/ckpt")]))
        pod = make_pod("opted-out", {"role": "train"})
        pod.metadata.annotations = {
            "podpreset.admission.ktpu.io/exclude": "true"}
        created = cs.pods.create(pod)
        assert not any(e.name == "CKPT_DIR"
                       for e in created.spec.containers[0].env)

    def test_absent_selector_matches_all(self, env):
        _, cs = env
        p = t.PodPreset()
        p.metadata.name = "match-all"
        p.spec.env = [t.EnvVar(name="GLOBAL", value="1")]
        cs.resource("podpresets").create(p)
        created = cs.pods.create(make_pod("anyone", {"whatever": "x"}))
        assert any(e.name == "GLOBAL"
                   for e in created.spec.containers[0].env)
