"""Write-path smoke guards (tier-1, non-slow).

Group-commit properties the write path must keep as the tree grows:

1. under a 16-writer create storm the store's fan-out coalesces — watch
   wakeups per delivered event < 1.0 (one queue wakeup serves a whole
   batch), and group-commit occupancy > 1;
2. batched and singleton commit paths produce BYTE-IDENTICAL watch
   frames — group commit is an amortization, never a wire-format fork;
3. the bulk-bind endpoint binds N pods in one request with per-item
   outcomes, and the scheduler's bulk path drives it correctly;
4. remote-store mode serves fresh reads WITHOUT a current_revision
   round-trip per GET (stream-progress freshness, the etcd
   progress-notify analog);
5. the write-path modules stay at zero ktpulint findings.
"""

import os
import threading
import time

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import NotFound
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store

from tests.helpers import make_node, make_tpu_pod
from tests.test_machinery import make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the modules this PR's write path lives in
WRITEPATH_MODULES = [
    "kubernetes1_tpu/storage/store.py",
    "kubernetes1_tpu/storage/server.py",
    "kubernetes1_tpu/storage/remote.py",
    "kubernetes1_tpu/storage/cacher.py",
    "kubernetes1_tpu/apiserver/registry.py",
    "kubernetes1_tpu/apiserver/server.py",
    "kubernetes1_tpu/scheduler/scheduler.py",
]


def key(pod):
    return f"/registry/pods/{pod.metadata.namespace}/{pod.metadata.name}"


class TestGroupCommitCoalescing:
    def test_wakeups_per_event_below_one_under_16_writers(self):
        """16 concurrent singleton writers must coalesce into shared
        drains: one fan-out wakeup covers a whole batch, so the
        wakeups-per-event ratio drops below 1.0 (it is exactly 1.0
        without group commit)."""
        store = Store(global_scheme)
        w = store.watch("/registry/pods/", queue_limit=0)
        barrier = threading.Barrier(16)

        def writer(k):
            barrier.wait()
            for i in range(25):
                pod = make_pod(f"gc{k}-{i}")
                store.create(key(pod), pod)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(16)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        assert not any(th.is_alive() for th in threads)
        try:
            assert store.watch_events == 400
            ratio = store.watch_wakeups / store.watch_events
            assert ratio < 1.0, (
                f"fan-out not coalescing: {store.watch_wakeups} wakeups "
                f"for {store.watch_events} events")
            assert store.commit_count == 400
            assert store.commit_batches < store.commit_count, \
                "every batch was a singleton — group commit is not grouping"
            # the watcher still received every event, in order
            revs = []
            while True:
                batch = w.next_batch_timeout(0.5)
                if batch is None:
                    break
                revs.extend(int(e.object["metadata"]["resourceVersion"])
                            for e in batch)
            assert len(revs) == 400 and revs == sorted(revs)
        finally:
            w.stop()
            store.close()

    def test_batched_and_singleton_commits_frame_identically(self):
        """The same object committed via create() and via commit_batch
        must produce byte-identical watch frames (separate schemes so the
        serialization cache cannot mask a divergence)."""
        s_single = Store(global_scheme.copy())
        s_batch = Store(global_scheme.copy())
        w1 = s_single.watch("/registry/pods/")
        w2 = s_batch.watch("/registry/pods/")
        try:
            pod = make_pod("framed")
            pod.metadata.uid = "uid-framed"
            pod.metadata.creation_timestamp = "2026-01-01T00:00:00Z"
            s_single.create(key(pod), pod)
            out = s_batch.commit_batch([{
                "op": "create", "key": key(pod),
                "obj": global_scheme.copy().encode(pod)}])
            assert "obj" in out[0]
            ev1 = w1.next_timeout(5)
            ev2 = w2.next_timeout(5)
            assert ev1 is not None and ev2 is not None
            f1 = s_single._scheme.watch_frame_bytes(ev1.type, ev1.object)
            f2 = s_batch._scheme.watch_frame_bytes(ev2.type, ev2.object)
            assert f1 == f2, (f1, f2)
            # and the committed state matches too
            assert s_single.list_raw("/registry/pods/")[0][0][2] == \
                s_batch.list_raw("/registry/pods/")[0][0][2]
        finally:
            w1.stop()
            w2.stop()
            s_single.close()
            s_batch.close()


class TestBulkBindEndpoint:
    def test_bulk_bind_per_item_outcomes(self):
        """One bindings:batch request binds every member and reports
        per-item outcomes — a bogus member fails alone."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.nodes.create(make_node("bb-n1", tpus=8))
            for i in range(4):
                cs.pods.create(make_tpu_pod(f"bb-{i}", tpus=1))
            bindings = []
            for i in range(4):
                b = t.Binding(
                    target_node="bb-n1",
                    extended_resource_assignments={
                        f"bb-{i}-tpu": [f"chip-{i}"]})
                b.metadata.name = f"bb-{i}"
                b.metadata.namespace = "default"
                bindings.append(b)
            ghost = t.Binding(target_node="bb-n1")
            ghost.metadata.name = "bb-ghost"
            ghost.metadata.namespace = "default"
            bindings.append(ghost)
            outcomes = cs.bind_batch("default", bindings)
            assert outcomes[:4] == [None] * 4
            assert isinstance(outcomes[4], NotFound)
            before_commits = master.store.commit_count
            for i in range(4):
                p = cs.pods.get(f"bb-{i}")
                assert p.spec.node_name == "bb-n1"
                assert p.spec.extended_resources[0].assigned == [f"chip-{i}"]
                # SLI stamp merged by the shared binding apply
                assert t.BOUND_AT_ANNOTATION in p.metadata.annotations
            assert master.store.commit_count == before_commits  # reads free
        finally:
            cs.close()
            master.stop()

    def test_scheduler_bind_many_uses_bulk_request(self):
        """The scheduler's _bind_many path drives bindings:batch: all
        members bound, batch-size histogram fed, failures handled
        per-item."""
        from kubernetes1_tpu.scheduler.scheduler import Scheduler, \
            ScheduleResult, _BindItem

        master = Master().start()
        cs = Clientset(master.url)
        sched = Scheduler(cs)  # not started: no informers needed here
        try:
            cs.nodes.create(make_node("sb-n1", tpus=8))
            items = []
            for i in range(3):
                cs.pods.create(make_tpu_pod(f"sb-{i}", tpus=1))
                pod = cs.pods.get(f"sb-{i}")
                result = ScheduleResult(
                    "sb-n1", {f"sb-{i}-tpu": [f"chip-{i}"]})
                binding = t.Binding(
                    target_node=result.node,
                    extended_resource_assignments=result.assignments)
                binding.metadata.name = pod.metadata.name
                binding.metadata.namespace = pod.metadata.namespace
                items.append(_BindItem(pod, pod.clone(), binding, result,
                                       None, ""))
            sched._bind_many("default", items)
            for i in range(3):
                assert cs.pods.get(f"sb-{i}").spec.node_name == "sb-n1"
            assert sched.binding_latency.count >= 1
        finally:
            sched.stop()
            cs.close()
            master.stop()

    def test_write_coalescing_window_correctness(self):
        """With the opt-in coalescing window armed, a concurrent create
        burst still lands every write exactly once (the window only
        delays, never drops or duplicates)."""
        master = Master(write_coalesce_window=0.003).start()
        cs_list = [Clientset(master.url) for _ in range(6)]
        try:
            barrier = threading.Barrier(6)
            errs = []

            def creator(k, ccs):
                barrier.wait()
                try:
                    for i in range(5):
                        ccs.pods.create(make_pod(f"wc{k}-{i}"))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=creator, args=(k, c))
                       for k, c in enumerate(cs_list)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=30)
            assert not errs
            pods, _ = cs_list[0].pods.list(namespace="default")
            assert len([p for p in pods
                        if p.metadata.name.startswith("wc")]) == 30
        finally:
            for c in cs_list:
                c.close()
            master.stop()


class TestBulkBindAuthz:
    def test_bulk_bind_requires_binding_subresource_permission(self):
        """bindings:batch must be gated by the SAME pods/binding
        permission as a singleton bind: create-pods alone is Forbidden,
        and a scheduler-shaped grant (pods/binding create) is enough."""
        from kubernetes1_tpu.machinery import Forbidden

        master = Master(
            authorization_mode="Node,RBAC",
            static_tokens={
                "admin-tok": ("system:admin", ["system:masters"]),
                "maker-tok": ("podmaker", []),
                "sched-tok": ("binder", []),
            }).start()
        admin_cs = Clientset(master.url, token="admin-tok")
        maker = Clientset(master.url, token="maker-tok")
        binder = Clientset(master.url, token="sched-tok")
        try:
            cr = t.ClusterRole(rules=[t.PolicyRule(
                verbs=["create", "get", "list"], resources=["pods"])])
            cr.metadata.name = "pod-maker"
            admin_cs.clusterroles.create(cr)
            crb = t.ClusterRoleBinding(
                subjects=[t.Subject(kind="User", name="podmaker")],
                role_ref=t.RoleRef(kind="ClusterRole", name="pod-maker"))
            crb.metadata.name = "podmaker-binding"
            admin_cs.clusterrolebindings.create(crb)
            cr2 = t.ClusterRole(rules=[t.PolicyRule(
                verbs=["create"], resources=["pods/binding"])])
            cr2.metadata.name = "pod-binder"
            admin_cs.clusterroles.create(cr2)
            crb2 = t.ClusterRoleBinding(
                subjects=[t.Subject(kind="User", name="binder")],
                role_ref=t.RoleRef(kind="ClusterRole", name="pod-binder"))
            crb2.metadata.name = "binder-binding"
            admin_cs.clusterrolebindings.create(crb2)

            maker.pods.create(make_pod("authz-p0"))
            b = t.Binding(target_node="some-node")
            b.metadata.name = "authz-p0"
            b.metadata.namespace = "default"
            # create-pods alone must NOT bind (escalation guard)
            try:
                maker.bind_batch("default", [b])
                raise AssertionError("bulk bind allowed without "
                                     "pods/binding permission")
            except Forbidden:
                pass
            # the binding-subresource grant is sufficient
            outcomes = binder.bind_batch("default", [b])
            assert outcomes == [None]
        finally:
            maker.close()
            binder.close()
            admin_cs.close()
            master.stop()


class TestRemoteFreshnessWithoutRPC:
    def test_reads_fresh_with_zero_current_revision_calls(self, tmp_path):
        """--store-address mode: the watch stream's progress revisions
        (and the client's own observed writes) replace the per-read
        current_revision round-trip — reads stay fresh with ZERO such
        RPCs."""
        from kubernetes1_tpu.storage.server import StoreServer

        store = Store(global_scheme.copy())
        server = StoreServer(store, str(tmp_path / "store.sock")).start()
        master = Master(store_address=str(tmp_path / "store.sock")).start()
        cs = Clientset(master.url)
        try:
            calls = []
            orig = master.store.current_revision

            def counting():
                calls.append(1)
                return orig()

            master.store.current_revision = counting
            for i in range(10):
                cs.pods.create(make_pod(f"rf-{i}"))
                # read-your-writes through the same apiserver, no RPC
                assert cs.pods.get(f"rf-{i}").metadata.name == f"rf-{i}"
            items, _ = cs.pods.list(namespace="default")
            assert len([p for p in items
                        if p.metadata.name.startswith("rf-")]) == 10
            assert not calls, (
                f"{len(calls)} current_revision round-trips on the read "
                f"path — stream-progress freshness regressed")
        finally:
            cs.close()
            master.stop()
            server.stop()

    def test_progress_heartbeat_advances_freshness(self, tmp_path):
        """A quiet stream still advances the cache's revision via progress
        heartbeats (so freshness never wedges on an idle cluster)."""
        import kubernetes1_tpu.storage.server as srv
        from kubernetes1_tpu.storage.remote import RemoteStore
        from kubernetes1_tpu.storage.cacher import Cacher

        old_hb = srv.WATCH_HEARTBEAT_SECONDS
        srv.WATCH_HEARTBEAT_SECONDS = 0.1
        store = Store(global_scheme.copy())
        server = srv.StoreServer(store, str(tmp_path / "hb.sock")).start()
        rs = RemoteStore(global_scheme.copy(), str(tmp_path / "hb.sock"))
        cacher = Cacher(rs, global_scheme.copy()).start()
        try:
            cacher.wait_fresh(timeout=5)
            # a commit OUTSIDE the cacher's /registry/ prefix bumps the
            # store revision without producing any event for this feed —
            # only the progress heartbeat can carry the new revision
            oob = make_pod("hb-oob")
            store.create("/oob/things/hb-oob", oob)
            target = store.current_revision()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                with cacher._cond:
                    if cacher._rev >= target:
                        break
                time.sleep(0.05)
            with cacher._cond:
                assert cacher._rev >= target, \
                    (cacher._rev, target, "progress never arrived")
            # and event-carried freshness still works alongside progress
            pod = make_pod("hb-peer")
            store.create(key(pod), pod)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if cacher.get_raw(key(pod)) is not None:
                    break
                time.sleep(0.05)
            assert cacher.get_raw(key(pod)) is not None
        finally:
            cacher.stop()
            rs.close()
            server.stop()
            srv.WATCH_HEARTBEAT_SECONDS = old_hb


class TestWritepathLintClean:
    def test_zero_ktpulint_findings_in_writepath_modules(self):
        from tools.ktpulint import lint_paths

        findings = lint_paths(
            [os.path.join(REPO, m) for m in WRITEPATH_MODULES])
        rendered = "\n".join(
            os.path.relpath(f.path, REPO) + f":{f.line}: {f.pass_id} "
            f"{f.message}" for f in findings)
        assert not findings, f"ktpulint findings:\n{rendered}"


class TestWritePathMetricsExported:
    def test_store_write_metrics_on_apiserver_metrics(self):
        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.pods.create(make_pod("wm-0"))
            import urllib.request

            raw = urllib.request.urlopen(
                master.url + "/metrics", timeout=5).read().decode()
            for name in ("ktpu_store_commits_total",
                         "ktpu_store_commit_batches_total",
                         "ktpu_store_batch_occupancy",
                         "ktpu_store_watch_wakeups_per_event",
                         "ktpu_store_wal_fsync_seconds",
                         "ktpu_write_coalesce_waits_total"):
                assert name in raw, name
        finally:
            cs.close()
            master.stop()
