"""utils/fasthttp parity: the fast header parser must be byte-for-byte
faithful to stdlib http.client.parse_headers on every behavior our HTTP
stack (or a peer) could observe — a parser differential between patched
and unpatched processes is request-smuggling surface, so parity is
asserted empirically against stdlib itself, not against expectations."""

import io
import http.client

from kubernetes1_tpu.utils.fasthttp import (
    _fast_parse_headers,
    _orig_parse_headers,
    install,
    uninstall,
)

CASES = [
    b"Host: x\r\nContent-Length: 3\r\n\r\n",
    b"A: v  \r\n\r\n",                      # trailing value spaces kept
    b"A:  two  spaces\r\n\r\n",             # leading stripped, inner kept
    b"A:\r\n\r\n",                          # empty value
    b"NoSpace:v\r\n\r\n",
    b"Dup: a\r\nDup: b\r\n\r\n",            # duplicates append
    b"A: one\r\n two\r\n\r\n",              # obs-fold keeps CRLF + spaces
    b"A: 1\r\n \r\nB: 2\r\n\r\n",           # whitespace-only continuation
    b"Good: 1\r\nBADLINE\r\nAfter: 2\r\n\r\n",  # defect line mid-block
    b"Name : v\r\nB: 2\r\n\r\n",            # space before colon
    b"\tBad: start\r\n\r\n",                # leading continuation
    b"A: one\r\n two\r\nBAD\r\nC: 3\r\n\r\n",   # fold then defect
    b"MiXeD-CaSe: yes\r\n\r\n",
    b"X: a\nY: b\n\n",                      # bare-LF line endings
    b"\r\n",                                # empty block
    # adversarial shapes from review: each must match stdlib EXACTLY
    b":x\r\nContent-Length: 5\r\n\r\n",     # empty header name
    b"From x\r\nHost: h\r\n\r\n",           # unix-From line
    b"Na me: v\r\nHost: h\r\n\r\n",         # space inside the name
    b"\x01Bad: v\r\nHost: h\r\n\r\n",       # control char in the name
    b"A: one\n two\n\n",                    # LF-terminated fold
    b"A: one\r\r\n cont\r\n\r\n",           # stray CR before CRLF
]


def _both(raw: bytes):
    std = _orig_parse_headers(io.BufferedReader(io.BytesIO(raw)))
    fast = _fast_parse_headers(io.BufferedReader(io.BytesIO(raw)))
    return std, fast


class TestParity:
    def test_items_identical_for_every_case(self):
        for raw in CASES:
            std, fast = _both(raw)
            assert list(std.items()) == list(fast.items()), raw

    def test_case_insensitive_get(self):
        _, fast = _both(b"Content-Type: json\r\n\r\n")
        assert fast.get("content-type") == "json"
        assert fast["CONTENT-TYPE"] == "json"

    def test_socket_consumption_identical(self):
        # framing safety: both must leave the stream at the same offset
        for raw in CASES:
            tail = b"PAYLOAD"
            s = io.BufferedReader(io.BytesIO(raw + tail))
            _orig_parse_headers(s)
            std_rest = s.read()
            f = io.BufferedReader(io.BytesIO(raw + tail))
            _fast_parse_headers(f)
            fast_rest = f.read()
            assert std_rest == fast_rest, raw

    def test_header_count_limit_matches_stdlib(self):
        # stdlib counts the blank terminator toward _MAXHEADERS, so a
        # block of exactly _MAXHEADERS headers RAISES — both must agree
        n = http.client._MAXHEADERS
        block = b"".join(b"H%d: v\r\n" % i for i in range(n)) + b"\r\n"
        import pytest

        with pytest.raises(http.client.HTTPException):
            _orig_parse_headers(io.BufferedReader(io.BytesIO(block)))
        with pytest.raises(http.client.HTTPException):
            _fast_parse_headers(io.BufferedReader(io.BytesIO(block)))
        ok = b"".join(b"H%d: v\r\n" % i for i in range(n - 1)) + b"\r\n"
        std, fast = _both(ok)
        assert list(std.items()) == list(fast.items())

    def test_fuzz_parity_random_blocks(self):
        import random

        rng = random.Random(31337)
        atoms = [b"Host: h\r\n", b"X-Y: v  \r\n", b" cont\r\n", b"BAD\r\n",
                 b":e\r\n", b"From x\r\n", b"A:\r\n", b"K:v\n", b"\tq\r\n",
                 b"Na me: v\r\n", b"Dup: 1\r\n", b"Dup: 2\r\n"]
        for _ in range(300):
            block = b"".join(rng.choice(atoms)
                             for _ in range(rng.randint(0, 8))) + b"\r\n"
            std, fast = _both(block)
            assert list(std.items()) == list(fast.items()), block

    def test_install_idempotent_and_reversible(self):
        try:
            install()
            install()
            assert http.client.parse_headers is _fast_parse_headers
        finally:
            uninstall()
            assert http.client.parse_headers is _orig_parse_headers
            install()  # other tests in the process expect it installed
