"""Shared test fixtures: nodes with synthetic TPU inventories, TPU pods."""

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.client import retry_on_conflict


def mutate_with_retry(rc, name, mutate, namespace="default"):
    """get → mutate(obj) → update under retry_on_conflict.

    Controllers writing status bump resourceVersion between our get and
    update, so every test-side read-modify-write goes through this.
    """

    def attempt():
        obj = rc.get(name, namespace=namespace)
        mutate(obj)
        return rc.update(obj)

    return retry_on_conflict(attempt)


def make_tpu_devices(count, slice_id="slice-0", tpu_type="v5e", host_index=0, prefix=None):
    prefix = prefix if prefix is not None else f"{slice_id}-h{host_index}"
    devices = []
    for i in range(count):
        devices.append(
            t.ExtendedResourceDevice(
                id=f"{prefix}-tpu{i}",
                health=t.DEVICE_HEALTHY,
                attributes={
                    t.ATTR_TPU_TYPE: tpu_type,
                    t.ATTR_TPU_SLICE: slice_id,
                    t.ATTR_TPU_HOST_INDEX: str(host_index),
                    t.ATTR_TPU_CHIP_COORDS: f"{i % 2},{i // 2},0",
                    t.ATTR_TPU_TOPOLOGY: "2x2x1",
                },
            )
        )
    return devices


def make_node(
    name,
    cpu="8",
    memory="32Gi",
    tpus=0,
    slice_id="slice-0",
    tpu_type="v5e",
    host_index=0,
    labels=None,
    ready=True,
):
    node = t.Node()
    node.metadata.name = name
    node.metadata.labels = labels or {}
    node.status.capacity = {"cpu": cpu, "memory": memory, "pods": "110"}
    node.status.allocatable = dict(node.status.capacity)
    node.status.conditions = [
        t.NodeCondition(type=t.NODE_READY, status="True" if ready else "False")
    ]
    if tpus:
        node.status.extended_resources = {
            "google.com/tpu": make_tpu_devices(
                tpus, slice_id=slice_id, tpu_type=tpu_type, host_index=host_index
            )
        }
    return node


def make_tpu_pod(name, tpus=1, ns="default", cpu="100m", affinity=None, priority=0,
                 gang="", gang_size=0):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    c = t.Container(name="main", image="jax-workload")
    c.resources.requests = {"cpu": cpu}
    pod.spec.containers = [c]
    pod.spec.priority = priority
    pod.spec.scheduling_gang = gang
    pod.spec.gang_size = gang_size
    if tpus:
        per = t.PodExtendedResource(
            name=f"{name}-tpu", resource="google.com/tpu", quantity=tpus,
            affinity=affinity,
        )
        pod.spec.extended_resources = [per]
        c.extended_resource_requests = [per.name]
    return pod
