"""CLI tests (ref: pkg/kubectl/cmd tests): drive ktpu commands against a
hollow LocalCluster through the real HTTP apiserver."""

import io
import json

import pytest
import yaml

from kubernetes1_tpu.cli import CLI, build_parser, dispatch
from kubernetes1_tpu.localcluster import LocalCluster
from kubernetes1_tpu.utils.waitutil import must_poll_until


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(nodes=2, tpus_per_node=4, hollow=True).start().wait_ready()
    yield c
    c.stop()


def run_cli(cluster, *argv):
    out = io.StringIO()
    cli = CLI(cluster.url, "default", out=out)
    args = build_parser().parse_args(["--server", cluster.url] + list(argv))
    try:
        dispatch(cli, args)
    finally:
        cli.cs.close()
    return out.getvalue()


def test_get_nodes_table(cluster):
    out = run_cli(cluster, "get", "nodes")
    assert "node-0" in out and "node-1" in out
    assert "Ready" in out
    assert "4/4" in out  # healthy/total chips


def test_apply_get_delete_roundtrip(cluster, tmp_path):
    manifest = {
        "kind": "Pod", "apiVersion": "v1",
        "metadata": {"name": "cli-pod"},
        "spec": {"containers": [{"name": "c", "image": "busybox",
                                 "command": ["sleep", "60"]}]},
    }
    f = tmp_path / "pod.yaml"
    f.write_text(yaml.safe_dump(manifest))
    out = run_cli(cluster, "apply", "-f", str(f))
    assert "pods/cli-pod created" in out

    out = run_cli(cluster, "get", "pods", "cli-pod", "-o", "json")
    assert json.loads(out)["metadata"]["name"] == "cli-pod"

    out = run_cli(cluster, "apply", "-f", str(f))  # idempotent re-apply
    assert "pods/cli-pod configured" in out

    out = run_cli(cluster, "describe", "pod", "cli-pod")
    assert "Name:         cli-pod" in out

    out = run_cli(cluster, "delete", "pod", "cli-pod")
    assert "deleted" in out


def test_deployment_scale_and_rollout(cluster, tmp_path):
    manifest = {
        "kind": "Deployment", "apiVersion": "apps/v1",
        "metadata": {"name": "web"},
        "spec": {
            "replicas": 2,
            "selector": {"matchLabels": {"app": "web"}},
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {"containers": [{"name": "c", "image": "busybox",
                                         "command": ["sleep", "300"]}]},
            },
        },
    }
    f = tmp_path / "deploy.yaml"
    f.write_text(yaml.safe_dump(manifest))
    run_cli(cluster, "apply", "-f", str(f))
    out = run_cli(cluster, "rollout", "status", "deployment/web", "--timeout", "30")
    assert "successfully rolled out" in out

    out = run_cli(cluster, "scale", "deployment/web", "--replicas", "3")
    assert "scaled to 3" in out
    must_poll_until(
        lambda: "3/3" in run_cli(cluster, "get", "deploy", "web"),
        timeout=30, desc="deployment scales to 3")
    run_cli(cluster, "delete", "deployment", "web")


def test_cordon_drain_uncordon(cluster):
    out = run_cli(cluster, "cordon", "node-1")
    assert "cordoned" in out
    out = run_cli(cluster, "get", "nodes")
    assert "SchedulingDisabled" in out
    out = run_cli(cluster, "drain", "node-1")
    assert "drained" in out
    run_cli(cluster, "uncordon", "node-1")
    assert "SchedulingDisabled" not in run_cli(cluster, "get", "nodes")


def test_top_nodes(cluster):
    out = run_cli(cluster, "top", "nodes")
    assert "TPU-USED" in out and "node-0" in out


def test_api_resources(cluster):
    out = run_cli(cluster, "api-resources")
    assert "pods" in out and "Pod" in out


def test_wait_for_delete(cluster):
    from tests.helpers import make_tpu_pod

    cli = CLI(cluster.url, "default", out=io.StringIO())
    cli.cs.pods.create(make_tpu_pod("wait-pod", tpus=0))
    cli.cs.pods.delete("wait-pod", "default", grace_seconds=0)
    out = run_cli(cluster, "wait", "pods/wait-pod", "--for", "delete", "--timeout", "20")
    assert "condition met" in out
    cli.cs.close()


def test_patch_verb(cluster, tmp_path):
    manifest = {
        "kind": "ConfigMap", "apiVersion": "v1",
        "metadata": {"name": "patch-me"},
        "data": {"a": "1"},
    }
    f = tmp_path / "cm.yaml"
    f.write_text(yaml.safe_dump(manifest))
    run_cli(cluster, "apply", "-f", str(f))
    out = run_cli(cluster, "patch", "configmap", "patch-me",
                  "-p", '{"data":{"b":"2"}}')
    assert "patched" in out
    got = json.loads(run_cli(cluster, "get", "configmaps", "patch-me",
                             "-o", "json"))
    assert got["data"] == {"a": "1", "b": "2"}


def test_label_and_annotate(cluster, tmp_path):
    manifest = {
        "kind": "ConfigMap", "apiVersion": "v1",
        "metadata": {"name": "label-me"},
    }
    f = tmp_path / "cm.yaml"
    f.write_text(yaml.safe_dump(manifest))
    run_cli(cluster, "apply", "-f", str(f))
    run_cli(cluster, "label", "configmap", "label-me", "tier=web")
    got = json.loads(run_cli(cluster, "get", "configmaps", "label-me",
                             "-o", "json"))
    assert got["metadata"]["labels"] == {"tier": "web"}

    # changing without --overwrite refuses
    import pytest as _pytest

    with _pytest.raises(SystemExit):
        run_cli(cluster, "label", "configmap", "label-me", "tier=db")
    run_cli(cluster, "label", "configmap", "label-me", "tier=db",
            "--overwrite")
    # key- removes
    run_cli(cluster, "label", "configmap", "label-me", "tier-")
    got = json.loads(run_cli(cluster, "get", "configmaps", "label-me",
                             "-o", "json"))
    assert not (got["metadata"].get("labels") or {})

    run_cli(cluster, "annotate", "configmap", "label-me", "note=hi")
    got = json.loads(run_cli(cluster, "get", "configmaps", "label-me",
                             "-o", "json"))
    assert got["metadata"]["annotations"]["note"] == "hi"


def test_edit_verb(cluster, tmp_path, monkeypatch):
    manifest = {
        "kind": "ConfigMap", "apiVersion": "v1",
        "metadata": {"name": "edit-me"},
        "data": {"k": "v0"},
    }
    f = tmp_path / "cm.yaml"
    f.write_text(yaml.safe_dump(manifest))
    run_cli(cluster, "apply", "-f", str(f))
    # EDITOR = a script that rewrites v0 -> v1 in place
    editor = tmp_path / "editor.sh"
    editor.write_text("#!/bin/sh\nsed -i 's/v0/v1/' \"$1\"\n")
    editor.chmod(0o755)
    monkeypatch.setenv("EDITOR", str(editor))
    out = run_cli(cluster, "edit", "configmap", "edit-me")
    assert "edited" in out
    got = json.loads(run_cli(cluster, "get", "configmaps", "edit-me",
                             "-o", "json"))
    assert got["data"]["k"] == "v1"


def test_rollout_history_and_undo(cluster):
    import time as _t

    manifest = {
        "kind": "Deployment", "apiVersion": "apps/v1",
        "metadata": {"name": "rollme"},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "rollme"}},
            "template": {
                "metadata": {"labels": {"app": "rollme"},
                             "annotations": {"ktpu.io/change-cause": "v1"}},
                "spec": {"containers": [{"name": "c", "image": "img:v1",
                                         "command": ["sleep", "60"]}]},
            },
        },
    }
    import tempfile

    import yaml as _yaml

    import os as _os

    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        _yaml.safe_dump(manifest, f)
        path = f.name
    run_cli(cluster, "apply", "-f", path)
    # rev 2: new image
    manifest["spec"]["template"]["spec"]["containers"][0]["image"] = "img:v2"
    manifest["spec"]["template"]["metadata"]["annotations"][
        "ktpu.io/change-cause"] = "v2"
    with open(path, "w") as f:
        _yaml.safe_dump(manifest, f)
    run_cli(cluster, "apply", "-f", path)

    deadline = _t.time() + 20
    while _t.time() < deadline:
        out = run_cli(cluster, "rollout", "history", "deployment/rollme")
        lines = [ln for ln in out.splitlines() if ln.strip()]
        if len(lines) >= 2:
            break
        _t.sleep(0.3)
    assert any(ln.startswith("1\t") and "v1" in ln for ln in lines), lines
    assert any(ln.startswith("2\t") and "v2" in ln for ln in lines), lines

    out = run_cli(cluster, "rollout", "undo", "deployment/rollme")
    assert "rolled back" in out
    from kubernetes1_tpu.client import Clientset

    cs = Clientset(cluster.url)
    try:
        dep = cs.deployments.get("rollme")
        assert dep.spec.template.spec.containers[0].image == "img:v1"
        # the rolled-back template becomes the NEW highest revision
        deadline = _t.time() + 20
        top = None
        while _t.time() < deadline:
            out = run_cli(cluster, "rollout", "history", "deployment/rollme")
            lines = [ln for ln in out.splitlines() if ln.strip()]
            top = lines[-1] if lines else None
            if top and top.startswith("3\t"):
                break
            _t.sleep(0.3)
        assert top is not None and top.startswith("3\t"), lines
    finally:
        cs.close()
        _os.unlink(path)


def test_three_way_apply_removes_dropped_fields(cluster, tmp_path):
    """THE r4 gap (Missing #3): removing a field from the manifest must
    remove it live on re-apply (last-applied-configuration 3-way, ref
    pkg/kubectl/cmd/apply.go:35-38)."""
    m = {
        "kind": "ConfigMap", "apiVersion": "v1",
        "metadata": {"name": "cfg3w",
                     "labels": {"team": "ml", "tier": "train"}},
        "data": {"lr": "3e-4", "batch": "256"},
    }
    f = tmp_path / "cm.yaml"
    f.write_text(yaml.safe_dump(m))
    run_cli(cluster, "apply", "-f", str(f))
    live = json.loads(run_cli(cluster, "get", "configmaps", "cfg3w",
                              "-o", "json"))
    assert live["metadata"]["labels"] == {"team": "ml", "tier": "train"}
    assert "kubectl.kubernetes.io/last-applied-configuration" in \
        live["metadata"]["annotations"]
    # drop a label and a data key; change another
    m["metadata"]["labels"] = {"team": "ml"}
    m["data"] = {"lr": "1e-4"}
    f.write_text(yaml.safe_dump(m))
    out = run_cli(cluster, "apply", "-f", str(f))
    assert "configured" in out
    live = json.loads(run_cli(cluster, "get", "configmaps", "cfg3w",
                              "-o", "json"))
    assert live["metadata"]["labels"] == {"team": "ml"}   # tier GONE
    assert live["data"] == {"lr": "1e-4"}                 # batch GONE
    run_cli(cluster, "delete", "configmaps", "cfg3w")


def test_three_way_apply_preserves_server_owned_fields(cluster, tmp_path):
    """apply must not clobber fields the manifest never specified
    (a controller-set label survives)."""
    m = {"kind": "ConfigMap", "apiVersion": "v1",
         "metadata": {"name": "cfg-owned"}, "data": {"a": "1"}}
    f = tmp_path / "cm2.yaml"
    f.write_text(yaml.safe_dump(m))
    run_cli(cluster, "apply", "-f", str(f))
    # a third party (controller) annotates the live object
    run_cli(cluster, "annotate", "configmaps", "cfg-owned",
            "owned-by=some-controller")
    m["data"] = {"a": "2"}
    f.write_text(yaml.safe_dump(m))
    run_cli(cluster, "apply", "-f", str(f))
    live = json.loads(run_cli(cluster, "get", "configmaps", "cfg-owned",
                              "-o", "json"))
    assert live["data"] == {"a": "2"}
    assert live["metadata"]["annotations"]["owned-by"] == "some-controller"
    run_cli(cluster, "delete", "configmaps", "cfg-owned")


def test_taint_add_and_remove(cluster):
    out = run_cli(cluster, "taint", "nodes", "node-0",
                  "dedicated=tpu:NoSchedule")
    assert "tainted" in out
    node = json.loads(run_cli(cluster, "get", "nodes", "node-0",
                              "-o", "json"))
    assert {"key": "dedicated", "value": "tpu",
            "effect": "NoSchedule"} in node["spec"]["taints"]
    out = run_cli(cluster, "taint", "node-0", "dedicated:NoSchedule-")
    node = json.loads(run_cli(cluster, "get", "nodes", "node-0",
                              "-o", "json"))
    # empty taints = default spec, elided from the wire entirely
    assert not node.get("spec", {}).get("taints")


def test_expose_deployment(cluster, tmp_path):
    m = {
        "kind": "Deployment", "apiVersion": "apps/v1",
        "metadata": {"name": "web"},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "web"}},
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {"containers": [{"name": "c", "image": "i",
                                         "command": ["sleep", "60"]}]}},
        },
    }
    f = tmp_path / "dep.yaml"
    f.write_text(yaml.safe_dump(m))
    run_cli(cluster, "apply", "-f", str(f))
    out = run_cli(cluster, "expose", "deployment", "web", "--port", "80",
                  "--target-port", "8080")
    assert "service/web exposed" in out
    svc = json.loads(run_cli(cluster, "get", "services", "web",
                             "-o", "json"))
    assert svc["spec"]["selector"] == {"app": "web"}
    assert svc["spec"]["ports"][0]["port"] == 80
    assert svc["spec"]["ports"][0]["targetPort"] == 8080
    run_cli(cluster, "delete", "services", "web")
    run_cli(cluster, "delete", "deployments", "web")


def test_auth_can_i(cluster):
    # LocalCluster runs AlwaysAllow: everything is yes
    out = run_cli(cluster, "auth", "can-i", "create", "pods")
    assert out.strip() == "yes"


def test_explain(cluster):
    out = run_cli(cluster, "explain", "pods")
    assert "KIND:     Pod" in out and "spec" in out
    out = run_cli(cluster, "explain", "pods.spec.containers")
    assert "Container" in out and "image" in out
    out = run_cli(cluster, "explain", "pods.spec.nodeName")
    assert "FIELD:" in out and "str" in out
