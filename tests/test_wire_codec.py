"""Binary wire fast path: codecs, negotiated length-prefixed framing,
and torn-frame failure semantics (machinery/codec.py, storage/wire.py,
the negotiate paths in storage/server.py + storage/remote.py)."""

import dataclasses
import json
import os
import pickle
import tempfile

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery.codec import (
    CodecError, JsonCodec, PyBin1Codec, get_codec, known_codecs)
from kubernetes1_tpu.machinery.meta import ObjectMeta
from kubernetes1_tpu.machinery.scheme import Scheme, global_scheme, to_dict
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.remote import RemoteStore
from kubernetes1_tpu.storage.server import StoreServer
from kubernetes1_tpu.utils import faultline


# ------------------------------------------------------------------ codecs


class TestCodecs:
    def test_pybin1_roundtrips_plain_data(self):
        doc = {"a": [1, 2.5, None, True, "x"], "nested": {"k": ["v"]},
               "bytes": b"raw payload"}
        assert PyBin1Codec.decode(PyBin1Codec.encode(doc)) == doc

    def test_pybin1_rejects_pickles_with_globals(self):
        # a pickle referencing ANY global must be refused before the name
        # resolves — the restricted Unpickler is what makes the binary
        # codec safe.  Any class instance's pickle references its class.
        hostile = pickle.dumps(ObjectMeta(name="evil"))
        with pytest.raises(CodecError):
            PyBin1Codec.decode(hostile)

    def test_pybin1_rejects_corrupt_payload(self):
        with pytest.raises(CodecError):
            PyBin1Codec.decode(b"\x80\x05garbage")

    def test_json_codec_roundtrip_and_corrupt(self):
        assert JsonCodec.decode(JsonCodec.encode({"a": 1})) == {"a": 1}
        with pytest.raises(CodecError):
            JsonCodec.decode(b"{not json")

    def test_registry(self):
        assert known_codecs() == ["json", "pybin1"]
        assert get_codec("pybin1") is PyBin1Codec
        with pytest.raises(ValueError):
            get_codec("nope")


class TestGoldenRoundTripEveryKind:
    """JSON and binary codecs must agree on EVERY registered kind: equal
    decoded objects and equal re-encoded JSON — driven off the scheme
    registry so new kinds are covered the moment they register."""

    def test_every_registered_kind(self):
        kinds = {kind: cls for kind, cls in global_scheme.by_kind.items()
                 if dataclasses.is_dataclass(cls)}
        assert len(kinds) > 20  # the registry is populated
        for kind, cls in sorted(kinds.items()):
            obj = cls()
            obj.metadata = ObjectMeta(
                name="golden", namespace="ns", uid=f"u-{kind}",
                resource_version="7", labels={"k": kind},
                annotations={"a": "1"})
            d = global_scheme.encode(obj)
            canonical = json.dumps(d, sort_keys=True)
            for codec in known_codecs():
                scheme = Scheme()  # fresh cache per codec pass
                raw = scheme.encode_bytes(d, codec=codec)
                d2 = scheme.decode_bytes(raw, codec=codec)
                assert json.dumps(d2, sort_keys=True) == canonical, \
                    f"{kind}: {codec} bytes did not round-trip the dict"
                back = global_scheme.decode(d2)
                assert to_dict(back) == to_dict(obj), \
                    f"{kind}: decoded object differs under {codec}"

    def test_cache_key_carries_codec_id(self):
        """One revision's JSON bytes and pybin1 bytes are INDEPENDENT
        cache entries: neither may be served for the other."""
        scheme = Scheme()
        pod = t.Pod()
        pod.metadata = ObjectMeta(name="p", namespace="ns", uid="u1",
                                  resource_version="5")
        d = global_scheme.encode(pod)
        raw_json = scheme.encode_bytes(d, codec="json")
        raw_bin = scheme.encode_bytes(d, codec="pybin1")
        assert raw_json != raw_bin
        json.loads(raw_json)  # JSON entry is real JSON
        # repeats hit the cache and return the exact same bytes
        assert scheme.encode_bytes(d, codec="json") == raw_json
        assert scheme.encode_bytes(d, codec="pybin1") == raw_bin
        hits, _misses = scheme.serialization_cache.stats()
        assert hits >= 2


# ----------------------------------------------------- negotiated framing


@pytest.fixture()
def store_pair():
    tmp = tempfile.mkdtemp(prefix="ktpu-wire-")
    sock = os.path.join(tmp, "s.sock")
    store = Store(global_scheme.copy())
    srv = StoreServer(store, sock).start()
    yield store, srv, sock
    srv.stop()


def _mkpod(name, rv_holder=None):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = "default"
    return pod


class TestBinaryWire:
    def test_crud_watch_equivalent_to_json(self, store_pair):
        _store, _srv, sock = store_pair
        results = {}
        for codec in ("json", "pybin1"):
            rs = RemoteStore(global_scheme.copy(), sock, codec=codec)
            w = rs.watch("/registry/pods/", 0)
            key = f"/registry/pods/default/p-{codec}"
            created = rs.create(key, _mkpod(f"p-{codec}"))
            assert rs.get(key).metadata.name == f"p-{codec}"
            items, rev = rs.list("/registry/pods/")
            assert any(p.metadata.name == f"p-{codec}" for p in items)
            evs = w.next_batch_timeout(5.0)
            assert evs and evs[0].type == "ADDED"
            assert evs[0].object["metadata"]["name"] == f"p-{codec}"
            # bulk ops cross the negotiated framing too
            raws = rs.get_raw_many([key, "/registry/pods/default/absent"])
            assert raws[0] is not None and raws[1] is None
            outs = rs.commit_batch([{
                "op": "update_cas", "key": key,
                "obj": raws[0],
                "expect_rv": raws[0]["metadata"]["resourceVersion"]}])
            assert "obj" in outs[0]
            results[codec] = {
                "name": created.metadata.name.replace(codec, "X"),
                "event_name": evs[0].object["metadata"]["name"]
                .replace(codec, "X"),
            }
            w.stop()
            rs.close()
        assert results["json"] == results["pybin1"]

    def test_unsupported_codec_falls_back_to_json(self, store_pair,
                                                  monkeypatch):
        """Old-server compat: a server that declines the negotiation
        leaves the connection on newline-JSON and everything still
        works — negotiation is an upgrade, not a gate."""
        from kubernetes1_tpu.storage import server as server_mod

        monkeypatch.setattr(server_mod, "known_codecs", lambda: ["json"])
        _store, _srv, sock = store_pair
        rs = RemoteStore(global_scheme.copy(), sock, codec="pybin1")
        key = "/registry/pods/default/fallback"
        rs.create(key, _mkpod("fallback"))
        assert rs.get(key).metadata.name == "fallback"
        # the pooled connection really is running the legacy protocol
        with rs._lock:
            assert rs._pool and rs._pool[-1][2] is None
        rs.close()

    def test_unknown_codec_rejected_at_construction(self, store_pair):
        _store, _srv, sock = store_pair
        with pytest.raises(ValueError):
            RemoteStore(global_scheme.copy(), sock, codec="zstd9000")

    def test_severed_rpc_frame_is_clean_transport_error(self, store_pair):
        """A length-prefixed frame severed mid-write must surface as a
        ConnectionError through the normal retry rules — never a hang,
        never a half-parsed request on the server."""
        _store, _srv, sock = store_pair
        rs = RemoteStore(global_scheme.copy(), sock, codec="pybin1")
        key = "/registry/pods/default/sever"
        rs.create(key, _mkpod("sever"))
        faultline.activate(3, "store.rpc=sever@1.0")
        try:
            with pytest.raises(ConnectionError):
                rs.get(key)
        finally:
            faultline.deactivate()
        # the torn connection was discarded; fresh dials work again
        assert rs.get(key).metadata.name == "sever"
        rs.close()

    def test_torn_watch_stream_closes_instead_of_hanging(self, store_pair):
        """store.watch faults tear the server's length-prefixed event
        frames mid-byte: the client watcher must observe a DEAD stream
        (closed=True, batch None) — the cacher's reseed cue — not a
        wedged read."""
        store, _srv, sock = store_pair
        rs = RemoteStore(global_scheme.copy(), sock, codec="pybin1")
        w = rs.watch("/registry/pods/", 0)
        faultline.activate(5, "store.watch=sever@1.0")
        try:
            store.create("/registry/pods/default/tear", _mkpod("tear"))
            deadline = 50
            while not w.closed and deadline:
                if w.next_batch_timeout(0.2) is None and w.closed:
                    break
                deadline -= 1
            assert w.closed, "torn watch stream never surfaced as dead"
            assert w.next_batch_timeout(0.2) is None
        finally:
            faultline.deactivate()
        w.stop()
        # a fresh watch after the faults lift streams cleanly again
        w2 = rs.watch("/registry/pods/", 0)
        store.create("/registry/pods/default/after", _mkpod("after"))
        evs = w2.next_batch_timeout(5.0)
        assert evs and evs[0].object["metadata"]["name"] == "after"
        w2.stop()
        rs.close()

    def test_apiserver_over_binary_store_wire(self, store_pair):
        """Master -> RemoteStore(pybin1) -> StoreServer: the full read/
        write path (registry, cacher seed, watch pump) over the binary
        framing."""
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset

        _store, _srv, sock = store_pair
        master = Master(store_address=sock, store_codec="pybin1").start()
        try:
            cs = Clientset(master.url)
            pod = _mkpod("via-api")
            pod.spec.containers = [t.Container(name="c", image="img")]
            cs.pods.create(pod)
            got = cs.pods.get("via-api", "default")
            assert got.metadata.name == "via-api"
            pods, _rv = cs.pods.list(namespace="default")
            assert any(p.metadata.name == "via-api" for p in pods)
            cs.close()
        finally:
            master.stop()
