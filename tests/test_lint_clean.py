"""Self-enforcing lint gate: the tree must stay at zero ktpulint findings.

This is the tier-1 half of the CI gate (`scripts/lint.py` is the
command-line half): any commit that introduces an unlocked mutation, a
blocking call under a lock, a swallowed exception, an undaemonized
thread, a wall-clock deadline, or an unsnapshotted iteration fails the
suite with the exact file:line: PASS-ID it must fix."""

import os

from tools.ktpulint import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tree_is_lint_clean():
    findings = lint_paths([os.path.join(REPO, "kubernetes1_tpu")])
    rendered = "\n".join(
        os.path.relpath(f.path, REPO) + f":{f.line}: {f.pass_id} {f.message}"
        for f in findings)
    assert not findings, f"ktpulint findings:\n{rendered}"


def test_tools_dir_is_lint_clean():
    """The linter holds itself to its own rules."""
    findings = lint_paths([os.path.join(REPO, "tools")])
    rendered = "\n".join(f.render() for f in findings)
    assert not findings, f"ktpulint findings in tools/:\n{rendered}"
