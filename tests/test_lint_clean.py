"""Self-enforcing lint gate: the tree must stay at zero ktpulint findings.

This is the tier-1 half of the CI gate (`scripts/lint.py` is the
command-line half): any commit that introduces an unlocked mutation, a
blocking call under a lock, a swallowed exception, an undaemonized
thread, a wall-clock deadline, an unsnapshotted iteration, a shared-
snapshot mutation (KTPU008), a typo'd raw-dict key (KTPU009), or a
bare suppression pragma (KTPU010) fails the suite with the exact
file:line: PASS-ID it must fix."""

import os

from tools.ktpulint import lint_paths
from tools.ktpulint.engine import bare_pragmas

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tree_is_lint_clean():
    findings = lint_paths([os.path.join(REPO, "kubernetes1_tpu")])
    rendered = "\n".join(
        os.path.relpath(f.path, REPO) + f":{f.line}: {f.pass_id} {f.message}"
        for f in findings)
    assert not findings, f"ktpulint findings:\n{rendered}"


def test_tools_dir_is_lint_clean():
    """The linter holds itself to its own rules."""
    findings = lint_paths([os.path.join(REPO, "tools")])
    rendered = "\n".join(f.render() for f in findings)
    assert not findings, f"ktpulint findings in tools/:\n{rendered}"


def test_every_pragma_is_justified():
    """Pragma-justification gate, explicitly and tree-wide (tests/ and
    scripts/ included — the lint gate itself only walks the package
    trees): a `# ktpulint: ignore[...]` without a justification is
    indistinguishable from quieting a bug, so KTPU010 covers every
    directory a pragma could hide in."""
    findings = []
    for tree in ("kubernetes1_tpu", "tools", "tests", "scripts"):
        root = os.path.join(REPO, tree)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for name in filenames:
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                with open(path, encoding="utf-8") as f:
                    findings.extend(
                        bare_pragmas(f.read().splitlines(), path))
    rendered = "\n".join(
        os.path.relpath(f.path, REPO) + f":{f.line}: {f.message}"
        for f in findings)
    assert not findings, f"unjustified ktpulint pragmas:\n{rendered}"
