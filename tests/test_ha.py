"""Control-plane HA: store behind its own socket, N stateless apiservers,
SIGKILL failover mid-Job.

Ref: the reference's L0 is a separately-clustered etcd behind stateless
apiservers (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:152,263);
kill any apiserver and the control plane keeps going.  The VERDICT r3 bar:
kill the active apiserver mid-Job (SIGKILL), the standby takes over, all
watches resume via resourceVersion, no write lost, the Job completes.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver.server import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.remote import RemoteStore
from kubernetes1_tpu.storage.server import StoreServer
from kubernetes1_tpu.utils.waitutil import must_poll_until

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestRemoteStore:
    """The split store: RemoteStore(unix socket) against StoreServer."""

    @pytest.fixture()
    def remote(self, tmp_path):
        store = Store(global_scheme.copy())
        server = StoreServer(store, str(tmp_path / "store.sock")).start()
        rs = RemoteStore(global_scheme.copy(), str(tmp_path / "store.sock"))
        yield rs, store
        rs.close()
        server.stop()

    def test_crud_roundtrip(self, remote):
        rs, _ = remote
        pod = t.Pod()
        pod.metadata.name = "p"
        pod.metadata.namespace = "d"
        created = rs.create("/registry/pods/d/p", pod)
        assert created.metadata.uid
        got = rs.get("/registry/pods/d/p")
        assert got.metadata.name == "p"
        got.metadata.labels = {"a": "b"}
        rs.update_cas("/registry/pods/d/p", got)
        items, rev = rs.list("/registry/pods/")
        assert len(items) == 1 and rev >= 2
        rs.delete("/registry/pods/d/p")
        assert rs.get_or_none("/registry/pods/d/p") is None

    def test_cas_conflict_and_guaranteed_update(self, remote):
        rs, _ = remote
        pod = t.Pod()
        pod.metadata.name = "p"
        rs.create("/registry/pods/d/p", pod)
        stale = rs.get("/registry/pods/d/p")
        fresh = rs.get("/registry/pods/d/p")
        fresh.metadata.labels = {"v": "1"}
        rs.update_cas("/registry/pods/d/p", fresh)
        from kubernetes1_tpu.machinery import Conflict

        stale.metadata.labels = {"v": "stale"}
        with pytest.raises(Conflict):
            rs.update_cas("/registry/pods/d/p", stale)

        def bump(obj):
            obj.metadata.labels["v"] = "2"
            return obj

        assert rs.guaranteed_update("/registry/pods/d/p",
                                    bump).metadata.labels["v"] == "2"

    def test_watch_streams_and_resumes(self, remote):
        rs, _ = remote
        pod = t.Pod()
        pod.metadata.name = "p"
        rs.create("/registry/pods/d/p", pod)
        _, rev = rs.list("/registry/pods/")
        w = rs.watch("/registry/pods/", since_rev=0)
        pod2 = t.Pod()
        pod2.metadata.name = "q"
        rs.create("/registry/pods/d/q", pod2)
        ev = w.next_timeout(5.0)
        assert ev is not None and ev.object["metadata"]["name"] == "q"
        w.stop()
        # resume from a known revision replays history
        w2 = rs.watch("/registry/pods/", since_rev=rev)
        ev2 = w2.next_timeout(5.0)
        assert ev2 is not None and ev2.object["metadata"]["name"] == "q"
        w2.stop()


class TestTwoMastersOneStore:
    def test_write_one_read_other_watch_crosses(self, tmp_path):
        store = Store(global_scheme.copy(),
                      wal_path=str(tmp_path / "store.wal"))
        ss = StoreServer(store, str(tmp_path / "store.sock")).start()
        m1 = Master(store_address=str(tmp_path / "store.sock")).start()
        m2 = Master(store_address=str(tmp_path / "store.sock")).start()
        try:
            c1, c2 = Clientset(m1.url), Clientset(m2.url)
            ns = t.Namespace()
            ns.metadata.name = "ha"
            c1.namespaces.create(ns, "")
            assert c2.namespaces.get("ha", "").metadata.name == "ha"
            with c2.pods.watch(namespace="ha") as w:
                pod = t.Pod()
                pod.metadata.name = "p1"
                pod.spec.containers = [t.Container(name="c", image="i")]
                c1.pods.create(pod, "ha")
                etype, obj = next(iter(w))
                assert (etype, obj["metadata"]["name"]) == ("ADDED", "p1")
            c1.close()
            c2.close()
        finally:
            m1.stop()
            m2.stop()
            ss.stop()


def _spawn(cmd, log):
    with open(log, "ab") as lf:  # child inherits a dup; parent's fd closes
        return subprocess.Popen(
            cmd, stdout=lf, stderr=subprocess.STDOUT,
            start_new_session=True,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
            cwd=REPO)


@pytest.fixture()
def ha_cluster(tmp_path, request):
    """store + 2 apiservers + KCM + scheduler + kubelet, all real
    processes; every client takes the two-server list.

    Leak discipline (VERDICT r4 Weak #2): the reaper is registered with
    addfinalizer BEFORE anything is spawned, so a setup failure — e.g.
    the health wait timing out on a loaded box — still kills every
    process already started.  A teardown placed after `yield` only runs
    when setup succeeds, which is exactly how ten store/apiserver pairs
    leaked onto the round-4 box."""
    d = str(tmp_path)
    sock = os.path.join(d, "store.sock")
    pa, pb = free_port(), free_port()
    servers = f"http://127.0.0.1:{pa},http://127.0.0.1:{pb}"
    py = sys.executable
    procs = {}
    clients = []

    def reap():
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        for p in procs.values():
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        for p in procs.values():  # collect exits: no zombies left behind
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass

    request.addfinalizer(reap)
    procs["store"] = _spawn(
        [py, "-m", "kubernetes1_tpu.storage", "--socket", sock,
         "--wal", os.path.join(d, "store.wal")],
        os.path.join(d, "store.log"))
    for name, port in (("api-a", pa), ("api-b", pb)):
        procs[name] = _spawn(
            [py, "-m", "kubernetes1_tpu.apiserver", "--port", str(port),
             "--store-address", sock],
            os.path.join(d, f"{name}.log"))
    cs = Clientset(servers)
    clients.append(cs)
    # BOTH apiservers must be individually healthy before the kill test has
    # meaning — a dead standby would pass a through-the-active-server check
    for port in (pa, pb):
        one = Clientset(f"http://127.0.0.1:{port}")
        clients.append(one)
        must_poll_until(lambda: _healthy(one), timeout=60.0,
                        desc=f"apiserver :{port} healthy")
    procs["kcm"] = _spawn(
        [py, "-m", "kubernetes1_tpu.controllers", "--server", servers],
        os.path.join(d, "kcm.log"))
    procs["sched"] = _spawn(
        [py, "-m", "kubernetes1_tpu.scheduler", "--server", servers,
         "--metrics-port", "-1"],
        os.path.join(d, "sched.log"))
    procs["kubelet"] = _spawn(
        [py, "-m", "kubernetes1_tpu.kubelet", "--server", servers,
         "--node-name", "ha-node", "--runtime", "fake",
         "--root-dir", os.path.join(d, "kubelet")],
        os.path.join(d, "kubelet.log"))
    yield {"cs": cs, "procs": procs, "servers": servers, "dir": d,
           "ports": (pa, pb)}


def _healthy(cs):
    try:
        cs.api.request("GET", "/healthz")
        return True
    except Exception:  # noqa: BLE001
        return False


class TestApiserverFailover:
    def test_sigkill_active_apiserver_mid_job(self, ha_cluster):
        env = ha_cluster
        cs = env["cs"]
        must_poll_until(
            lambda: any(c.type == "Ready" and c.status == "True"
                        for n in cs.nodes.list()[0]
                        for c in n.status.conditions),
            timeout=30.0, desc="node Ready")
        job = t.Job()
        job.metadata.name = "ha-job"
        job.spec.completions = 4
        job.spec.parallelism = 2
        pod_t = t.PodTemplateSpec()
        pod_t.spec.restart_policy = "Never"
        pod_t.spec.containers = [t.Container(
            name="w", image="img", command=["sleep", "1"])]
        job.spec.template = pod_t
        cs.jobs.create(job, "default")
        # wait until the job is actually in flight (pods exist)
        must_poll_until(
            lambda: len(cs.pods.list(namespace="default")[0]) >= 1,
            timeout=30.0, desc="job pods created")
        # a write landed just before the kill must survive it
        marker = t.ConfigMap(data={"written": "before-kill"})
        marker.metadata.name = "pre-kill-marker"
        cs.configmaps.create(marker, "default")
        # SIGKILL the ACTIVE apiserver (the one this client — and every
        # component, since all start at index 0 — is talking to)
        active_name = "api-a" if cs.api._active == 0 else "api-b"
        os.killpg(env["procs"][active_name].pid, signal.SIGKILL)
        # the standby takes over: job completes, nothing lost (generous
        # timeout: this drives 6 real processes on a 1-CPU CI box)
        must_poll_until(
            lambda: (cs.jobs.get("ha-job", "default").status.succeeded
                     or 0) >= 4,
            timeout=240.0, desc="job completes through the standby apiserver")
        assert cs.configmaps.get(
            "pre-kill-marker", "default").data["written"] == "before-kill"
        # the client did fail over
        assert ("api-a" if cs.api._active == 0 else "api-b") != active_name

    def test_watches_resume_after_kill(self, ha_cluster):
        env = ha_cluster
        cs = env["cs"]
        must_poll_until(lambda: _healthy(cs), timeout=20.0, desc="healthy")
        seen = []
        import threading

        stop = threading.Event()

        def watch_loop():
            # the reflector pattern: rewatch from last rv on stream death
            rv = cs.configmaps.list(namespace="default")[1]
            while not stop.is_set():
                try:
                    with cs.configmaps.watch(namespace="default",
                                             resource_version=rv) as w:
                        for etype, obj in w:
                            seen.append(obj["metadata"]["name"])
                            rv = obj["metadata"]["resourceVersion"]
                except Exception:  # noqa: BLE001
                    time.sleep(0.2)

        thr = threading.Thread(target=watch_loop, daemon=True)
        thr.start()
        active_name = "api-a" if cs.api._active == 0 else "api-b"
        os.killpg(env["procs"][active_name].pid, signal.SIGKILL)
        time.sleep(0.5)
        after = t.ConfigMap(data={"k": "v"})
        after.metadata.name = "post-kill-event"
        must_poll_until(lambda: _try_create(cs, after), timeout=20.0,
                        desc="write through standby")
        must_poll_until(lambda: "post-kill-event" in seen, timeout=20.0,
                        desc="watch resumed and saw the post-kill event")
        stop.set()


def _try_create(cs, obj):
    try:
        cs.configmaps.create(obj, "default")
        return True
    except Exception:  # noqa: BLE001
        return False


class TestFixtureLeakDiscipline:
    """VERDICT r4 Weak #2: a fixture whose setup fails must reap what it
    already spawned — ten store/apiserver pairs leaked onto the round-4
    box precisely because teardown lived after `yield`."""

    def test_setup_failure_reaps_spawned_processes(self, tmp_path, request,
                                                   monkeypatch):
        # make the health wait unpassable and fast
        monkeypatch.setattr(sys.modules[__name__], "_healthy",
                            lambda cs: False)
        orig = must_poll_until
        monkeypatch.setattr(
            sys.modules[__name__], "must_poll_until",
            lambda fn, timeout=60.0, desc="": orig(fn, timeout=2.0,
                                                   desc=desc))
        gen = ha_cluster.__wrapped__(tmp_path, request)
        with pytest.raises(Exception):
            next(gen)  # spawns store + 2 apiservers, then health wait fails
        # Setup really did spawn processes before failing:
        sock = os.path.join(str(tmp_path), "store.sock")
        out = subprocess.run(
            ["ps", "axww"], capture_output=True, text=True).stdout
        mine = [line for line in out.splitlines() if sock in line]
        assert mine, "setup should have spawned store/apiservers"
        # The reaper was registered on THIS request via addfinalizer, so it
        # runs at this test's teardown — and the session-scoped leak police
        # (tests/conftest.py) fails the whole run if it doesn't kill them.
        # Nothing more to assert here: the guarantee is the pair of them.
